"""Sharded RecordIO streaming with a checkpointable cursor.

Reference: the distributed split of src/io/iter_image_recordio_2.cc
(part_index/num_parts record partitioning) rebuilt for elastic TPU
training (docs/sharded_training.md):

* **static file ownership** — rank ``r`` of ``world`` owns
  ``files[r::world]``. When ``world > len(files)`` the ranks sharing file
  ``f`` stride its index (``keys[sub::nsub]``), so every record is owned
  by exactly one rank per epoch at any world size — no central iterator,
  no handshake.
* **deterministic per-epoch shuffle** — the epoch's record order is a
  pure function of ``(seed, epoch)``; every generation of a restarted
  rank reproduces it exactly, which is what makes the cursor meaningful.
* **checkpointable cursor** — ``state()``/``set_state()`` capture
  (epoch, position); ``module.fit`` stores it in the CheckpointManager
  meta on preemption so resume re-enters the SAME epoch order at the
  exact record boundary (PR-17 mid-epoch resume-equivalence) instead of
  blindly fast-forwarding.
"""
from __future__ import annotations

import os

import numpy as _np

from ..base import MXNetError
from ..io import DataBatch, DataDesc, DataIter
from .. import ndarray as nd

__all__ = ["ShardedRecordStream", "StreamDataIter"]


def _epoch_rng(seed, epoch):
    # mixed so (seed, epoch) pairs land on distinct streams; modulo keeps
    # it a legal RandomState seed
    return _np.random.RandomState((seed * 1000003 + epoch) % (2 ** 32))


class ShardedRecordStream:
    """This rank's deterministic stream of RecordIO records.

    ``files`` — list of ``.rec`` paths (each needs its ``.idx`` sibling:
    striding and shuffle are random-access) or explicit ``(idx, rec)``
    pairs. One instance per rank; ranks never communicate."""

    def __init__(self, files, rank=0, world=1, shuffle=False, seed=0):
        if not files:
            raise MXNetError("ShardedRecordStream: no record files")
        if not 0 <= rank < world:
            raise MXNetError("ShardedRecordStream: rank %d outside world %d"
                             % (rank, world))
        self._files = []
        for f in files:
            if isinstance(f, (tuple, list)):
                idx_path, rec_path = f
            else:
                rec_path = f
                idx_path = os.path.splitext(f)[0] + ".idx"
            if not os.path.exists(idx_path):
                raise MXNetError(
                    "ShardedRecordStream: %s has no index file %s (striding "
                    "and shuffle need random access — build one with "
                    "tools/rec2idx.py)" % (rec_path, idx_path))
            self._files.append((idx_path, rec_path))
        self.rank = int(rank)
        self.world = int(world)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        nfiles = len(self._files)
        if self.world <= nfiles:
            # whole files, strided over ranks
            self._owned = [(i, 0, 1) for i in range(self.rank, nfiles,
                                                    self.world)]
        else:
            # more ranks than files: the ranks sharing file f stride its
            # key list — still exactly-once coverage per epoch
            f = self.rank % nfiles
            nsub = (self.world - f - 1) // nfiles + 1
            self._owned = [(f, self.rank // nfiles, nsub)]
        self._readers = {}
        self._keys = {}
        self._epoch = 0
        self._pos = 0
        self._order = self._build_order(0)

    def _reader(self, file_idx):
        r = self._readers.get(file_idx)
        if r is None:
            from .. import recordio

            idx_path, rec_path = self._files[file_idx]
            r = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
            self._readers[file_idx] = r
        return r

    def _file_keys(self, file_idx):
        keys = self._keys.get(file_idx)
        if keys is None:
            keys = list(self._reader(file_idx).keys)
            self._keys[file_idx] = keys
        return keys

    def _build_order(self, epoch):
        order = [(fi, k) for fi, sub, nsub in self._owned
                 for k in self._file_keys(fi)[sub::nsub]]
        if self.shuffle:
            perm = _epoch_rng(self.seed, epoch).permutation(len(order))
            order = [order[i] for i in perm]
        return order

    def __len__(self):
        return len(self._order)

    @property
    def epoch(self):
        return self._epoch

    @property
    def position(self):
        return self._pos

    def next_record(self):
        """Raw bytes of the next owned record; StopIteration ends the
        epoch (advance_epoch() starts the next one)."""
        if self._pos >= len(self._order):
            raise StopIteration
        file_idx, key = self._order[self._pos]
        self._pos += 1
        return self._reader(file_idx).read_idx(key)

    def advance_epoch(self):
        self._epoch += 1
        self._pos = 0
        self._order = self._build_order(self._epoch)

    def state(self):
        """Checkpointable cursor (JSON-safe)."""
        return {"version": 1, "epoch": self._epoch, "pos": self._pos,
                "seed": self.seed, "rank": self.rank, "world": self.world,
                "nfiles": len(self._files)}

    def set_state(self, st):
        """Restore a cursor. The topology must match — a cursor taken at a
        different (rank, world, file-set, seed) indexes a DIFFERENT record
        order, and silently resuming there would double/drop records."""
        for key, mine in (("rank", self.rank), ("world", self.world),
                          ("seed", self.seed),
                          ("nfiles", len(self._files))):
            if int(st.get(key, mine)) != mine:
                raise MXNetError(
                    "ShardedRecordStream.set_state: cursor %s=%s does not "
                    "match this stream's %s=%s — resuming it here would "
                    "break exactly-once coverage" % (key, st.get(key), key,
                                                     mine))
        self._epoch = int(st["epoch"])
        self._order = self._build_order(self._epoch)
        pos = int(st["pos"])
        if not 0 <= pos <= len(self._order):
            raise MXNetError("ShardedRecordStream.set_state: pos %d outside "
                             "epoch of %d records" % (pos, len(self._order)))
        self._pos = pos

    def close(self):
        for r in self._readers.values():
            r.close()
        self._readers = {}


class StreamDataIter(DataIter):
    """DataIter over a ShardedRecordStream with optional pipelined decode
    workers (``mxtpu-data-worker-*``) and the checkpointable cursor.

    ``decode_fn(record_bytes) -> (data, label)`` runs per sample — on the
    worker pool when ``workers > 0``, inline otherwise; delivery order is
    source order either way. ``reset()`` advances to the next epoch (the
    ``module.fit`` contract: one reset per epoch; the first reset on a
    fresh iterator is a no-op so epoch 0 is not skipped), except
    immediately after ``set_state()``, which arms a one-shot skip so the
    restored cursor survives fit's epoch-top reset."""

    def __init__(self, stream, batch_size, decode_fn, data_shape,
                 label_shape=(), data_name="data",
                 label_name="softmax_label", workers=0, depth=None):
        super().__init__(batch_size)
        self._stream = stream
        self._decode = decode_fn
        self._data_shape = tuple(data_shape)
        self._label_shape = tuple(label_shape)
        self._data_name = data_name
        self._label_name = label_name
        self._pool = None
        if workers > 0:
            from .core import DecodePool

            self._pool = DecodePool(
                stream.next_record, decode_fn, workers=workers,
                depth=depth if depth is not None else 2 * workers,
                owner="StreamDataIter")
        self._delivered = 0
        self._skip_reset = False

    @property
    def provide_data(self):
        return [DataDesc(self._data_name,
                         (self.batch_size,) + self._data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self._label_name,
                         (self.batch_size,) + self._label_shape)]

    def _next_sample(self):
        if self._pool is not None:
            return self._pool.get()
        return self._decode(self._stream.next_record())

    def next(self):
        batch_data = []
        batch_label = []
        pad = 0
        for _ in range(self.batch_size):
            try:
                data, label = self._next_sample()
            except StopIteration:
                if not batch_data:
                    raise
                pad = self.batch_size - len(batch_data)
                k = 0
                while len(batch_data) < self.batch_size:
                    batch_data.append(batch_data[k])
                    batch_label.append(batch_label[k])
                    k += 1
                break
            batch_data.append(_np.asarray(data, dtype=_np.float32))
            batch_label.append(_np.asarray(label, dtype=_np.float32))
        self._delivered += self.batch_size - pad
        return DataBatch(data=[nd.array(_np.stack(batch_data))],
                         label=[nd.array(_np.stack(batch_label))], pad=pad)

    def reset(self):
        if self._skip_reset:
            # one-shot: set_state() just restored a mid-epoch cursor and
            # fit's epoch-top reset must not advance past it
            self._skip_reset = False
            return
        if self._pool is not None:
            self._pool.reset()
        if self._delivered == 0 and self._stream.position == 0:
            return  # fresh iterator: first reset must not skip epoch 0
        self._stream.advance_epoch()
        self._delivered = 0

    def state(self):
        """Cursor in DELIVERED samples — read-ahead by the decode pool is
        deliberately excluded, so a checkpoint taken between batches
        describes exactly what the consumer has seen."""
        st = self._stream.state()
        st["pos"] = self._delivered
        return st

    def set_state(self, st):
        if self._pool is not None:
            self._pool.reset()
        self._stream.set_state(st)
        self._delivered = int(st["pos"])
        self._skip_reset = True

    def close(self):
        """Join pipeline threads and release record readers (clean
        shutdown on close/preemption)."""
        if self._pool is not None:
            self._pool.close()
        self._stream.close()
