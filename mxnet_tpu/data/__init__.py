"""mxnet_tpu.data — the asynchronous input pipeline.

The shared core (``PrefetchBuffer``/``DecodePool``) behind every
prefetching surface in the library, the NamedSharding-aware device
prefetcher, and sharded RecordIO streaming with a checkpointable cursor.
Architecture and sizing math: docs/data_pipeline.md."""
from .core import DecodePool, PrefetchBuffer
from .device_prefetch import DevicePrefetcher, place_batch
from .sharded_stream import ShardedRecordStream, StreamDataIter

__all__ = ["PrefetchBuffer", "DecodePool", "DevicePrefetcher",
           "place_batch", "ShardedRecordStream", "StreamDataIter"]
