"""Device prefetcher: double-buffered async host->device staging.

The missing half of the prefetch story: ``PrefetchBuffer`` overlaps host
decode with compute, but the step still paid the host->device copy
synchronously. ``DevicePrefetcher`` moves that copy onto the producer
thread as an *async* ``jax.device_put`` — PJRT starts the transfer and
returns immediately, so batch N+1's copy (and the decode behind it)
overlaps batch N's compute, and the consumer receives device arrays that
are already (or nearly) resident when the step launches.

With a ``mesh``, placement is ``NamedSharding``-aware: every batch leaf
is put with ``batch_spec(mesh, ndim)`` — the exact in_sharding the
ShardedTrainer fused step compiles against — so ``step_batch`` consumes
already-sharded arrays and ``executor._place_inputs`` is a no-op (no
second copy, no resharding at dispatch).

Cursor semantics: each staged batch carries the inner iterator's
``state()`` snapshot taken right after it was produced; ``state()`` here
returns the snapshot of the last batch the CONSUMER received, so a
checkpoint taken between steps describes exactly the batches the model
has seen — not the batches the pipeline read ahead.
"""
from __future__ import annotations

from .. import env as _env
from ..base import MXNetError
from ..ndarray import NDArray
from .core import PrefetchBuffer

__all__ = ["DevicePrefetcher", "place_batch"]


def _batch_sharding(mesh, ndim):
    import jax.sharding as jsh

    from ..parallel.sharding import batch_spec, named_sharding

    if ndim == 0:
        return named_sharding(mesh, jsh.PartitionSpec())  # replicate scalars
    return named_sharding(mesh, batch_spec(mesh, ndim))


def _place_leaf(x, mesh):
    import jax

    if isinstance(x, NDArray):
        return NDArray(_place_leaf(x._data, mesh))
    arr = x
    if mesh is None:
        return jax.device_put(arr)
    ndim = getattr(arr, "ndim", None)
    if ndim is None:
        return jax.device_put(arr)
    return jax.device_put(arr, _batch_sharding(mesh, ndim))


def place_batch(batch, mesh=None):
    """Start async device transfers for every array leaf of a batch.

    Handles ``DataBatch`` (data/label lists), NDArray, numpy/jax arrays,
    and (possibly nested) lists/tuples/dicts of those; anything else
    passes through untouched. Returns the same structure with every array
    leaf replaced by its device-resident (sharded, when ``mesh`` is
    given) counterpart."""
    from ..io import DataBatch

    if isinstance(batch, DataBatch):
        return DataBatch(
            data=place_batch(batch.data, mesh),
            label=place_batch(batch.label, mesh),
            pad=batch.pad, index=batch.index, bucket_key=batch.bucket_key,
            provide_data=batch.provide_data,
            provide_label=batch.provide_label)
    if isinstance(batch, (list, tuple)):
        return type(batch)(place_batch(b, mesh) for b in batch)
    if isinstance(batch, dict):
        return {k: place_batch(v, mesh) for k, v in batch.items()}
    if isinstance(batch, NDArray) or hasattr(batch, "ndim"):
        return _place_leaf(batch, mesh)
    return batch


class DevicePrefetcher:
    """Bounded double-buffered queue of async device transfers over any
    iterator/DataIter of batches.

    depth (default ``MXTPU_DATA_PREFETCH_DEPTH``) batches are staged
    ahead; the producer thread pulls the inner iterator, starts the
    device_put, and queues the placed batch. Iterator protocol plus the
    DataIter surface the training loops use (``next``/``reset``/
    ``provide_data``/``provide_label``), plus the checkpointable cursor
    passthrough (``state``/``set_state``) when the inner iterator has
    one."""

    def __init__(self, it, depth=None, mesh=None, src="fit"):
        if depth is None:
            depth = _env.get("MXTPU_DATA_PREFETCH_DEPTH")
        self._it = it
        self._depth = max(1, int(depth))
        self._mesh = mesh
        self._src = src
        self.batch_size = getattr(it, "batch_size", 0)
        self._buf = None
        self._last_state = None

    @property
    def provide_data(self):
        return self._it.provide_data

    @property
    def provide_label(self):
        return self._it.provide_label

    def _produce(self):
        batch = next(self._it)
        placed = place_batch(batch, self._mesh)
        st = self._it.state() if hasattr(self._it, "state") else None
        return (st, placed)

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        if self._buf is None:
            self._buf = PrefetchBuffer(
                self._produce, depth=self._depth,
                name="mxtpu-data-device-prefetch",
                owner="DevicePrefetcher", src=self._src)
        st, batch = self._buf.get()
        if st is not None:
            # the cursor the checkpoint should record: batches DELIVERED,
            # not batches the pipeline read ahead
            self._last_state = st
        return batch

    def reset(self):
        self.close()
        self._it.reset()

    def close(self):
        """Stop + join the producer (clean shutdown / preemption path)."""
        if self._buf is not None:
            self._buf.close()
            self._buf = None

    def state(self):
        if self._last_state is not None:
            return self._last_state
        if hasattr(self._it, "state"):
            return self._it.state()
        raise MXNetError("DevicePrefetcher: inner iterator %r has no "
                         "state()" % (type(self._it).__name__,))

    def set_state(self, st):
        if not hasattr(self._it, "set_state"):
            raise MXNetError("DevicePrefetcher: inner iterator %r has no "
                             "set_state()" % (type(self._it).__name__,))
        self.close()
        self._it.set_state(st)
        self._last_state = None
