"""Monitor: per-op output statistics during training.

TPU-native equivalent of the reference's `python/mxnet/monitor.py` (class
Monitor: installs an executor monitor callback, collects a stat per output
NDArray each batch between `tic()`/`toc()`, prints sorted rows — reference
monitor.py:34; executor hook graph_executor.cc:1319-1341). Works with
Executors (`install(exe)` -> `set_monitor_callback`) and with Modules
(`module.install_monitor(mon)`, which forwards to the bound executors —
reference: module.py install_monitor).
"""
from __future__ import annotations

import re

from . import log as _log
from . import telemetry
from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]

_LOG = _log.get_logger("mxnet_tpu.monitor", level=_log.INFO)


class Monitor:
    """reference: monitor.py:34.

    Parameters
    ----------
    interval : batches between collections
    stat_func : NDArray -> NDArray statistic (default: mean(|x|))
    pattern : regex on output name
    sort : sort output rows by name
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                return x.abs().mean()

        self.interval = interval
        self.stat_func = stat_func
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.queue = []
        self.step = 0
        self.activated = False
        self.exes = []

    def stat_helper(self, name, arr):
        if not self.activated or not self.re_pattern.match(name):
            return
        if isinstance(arr, NDArray):
            self.queue.append((self.step, name, self.stat_func(arr)))

    def install(self, exe):
        """Attach to an executor, or anything exposing install_monitor
        (Module) (reference: monitor.py install_to_executor)."""
        if hasattr(exe, "set_monitor_callback"):
            exe.set_monitor_callback(self.stat_helper)
        else:
            exe.install_monitor(self)
        self.exes.append(exe)

    install_to_executor = install

    def tic(self):
        """Start collecting for this batch (reference: monitor.py:87)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Finish the batch, return [(step, name, stat_str)] (reference:
        monitor.py:95)."""
        if not self.activated:
            return []
        self.activated = False
        res = []
        queue = self.queue
        if self.sort:
            queue = sorted(queue, key=lambda q: q[1])
        for step, name, stat in queue:
            if isinstance(stat, NDArray):
                stat = str(stat.asnumpy().reshape(-1)[:10].tolist())
            res.append((step, name, stat))
        self.queue = []
        return res

    def toc_print(self):
        """reference: monitor.py:118 — routed through mxnet_tpu.log instead
        of bare print, and counted in telemetry so monitored runs are
        visible in the JSONL stream too."""
        rows = self.toc()
        telemetry.counter("mxtpu_monitor_rows_total").inc(len(rows))
        for step, name, stat in rows:
            _LOG.info("Batch: %7d %30s %s", step, name, stat)
