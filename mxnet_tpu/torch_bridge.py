"""Torch op bridge (plugin parity).

Reference: python/mxnet/torch.py + plugin/torch — exposes Torch tensor
functions/criterions as MXNet operators. The TPU-native analogue runs the
torch computation on the HOST (torch-cpu) and exchanges tensors zero-copy
via DLPack (ndarray.from_dlpack / to_dlpack_for_read); gradients flow
through the autograd tape by delegating the node's backward to
torch.autograd — the same plugin-op shape as CustomOp (operator.py), with
torch as the kernel author instead of numpy.

Like the reference's plugin this is an interop escape hatch, not a compute
path: anything inside `jit`/hybridize stays pure-XLA, and a bridged op
forces a host sync (documented; the reference's torch plugin likewise ran
outside the graph compiler's reach).

    import mxnet_tpu as mx
    from mxnet_tpu import torch_bridge as th

    softshrink = th.function(lambda t: torch.nn.functional.softshrink(t))
    y = softshrink(x_nd)              # NDArray in, NDArray out
    y.backward()                      # tape-integrated via torch.autograd
"""
from __future__ import annotations

from .autograd import Function
from .base import MXNetError

__all__ = ["available", "to_torch", "from_torch", "function", "criterion"]


def _torch():
    try:
        import torch

        return torch
    except ImportError:
        raise MXNetError("torch is not installed; the torch bridge needs "
                         "torch-cpu (reference plugin/torch analogue)")


def available():
    try:
        _torch()
        return True
    except MXNetError:
        return False


def to_torch(arr):
    """NDArray -> torch.Tensor (host, zero-copy via DLPack where possible).

    torch-cpu cannot import an accelerator DLPack capsule, so when the
    buffer lives on a TPU/GPU device it is copied to the host first (the
    documented host-sync of every bridged op); zero-copy only on CPU."""
    torch = _torch()
    import numpy as _np

    data = arr._data
    try:
        on_cpu = all(d.platform == "cpu" for d in data.devices())
    except Exception:  # noqa: BLE001 — fall back to the safe host copy
        on_cpu = False
    if not on_cpu:
        # np.asarray(jax_array) is read-only and numpy refuses DLPack
        # export of read-only buffers; from_numpy on a fresh copy instead
        return torch.from_numpy(_np.array(data, copy=True))
    return torch.from_dlpack(arr.to_dlpack_for_read())


def from_torch(tensor):
    """torch.Tensor -> NDArray."""
    from . import ndarray as nd

    return nd.from_dlpack(tensor.detach().contiguous())


class _TorchFn(Function):
    """One bridged call: forward runs the torch fn under torch.enable_grad,
    backward asks torch.autograd for input grads (the reference's torch
    plugin pairs TH forward/backward entry points the same way)."""

    def __init__(self, fn, kwargs):
        super().__init__()
        self._fn = fn
        self._kwargs = kwargs
        self._tin = None
        self._tout = None

    def forward(self, *inputs):
        torch = _torch()
        # int inputs (embedding indices, masks) cannot require grad
        tins = [to_torch(a).detach().clone() for a in inputs]
        self._tin = [t.requires_grad_(bool(t.is_floating_point()))
                     for t in tins]
        with torch.enable_grad():
            out = self._fn(*self._tin, **self._kwargs)
        self._tout = out if isinstance(out, (tuple, list)) else (out,)
        res = tuple(from_torch(t) for t in self._tout)
        return res if len(res) > 1 else res[0]

    def backward(self, *ograds):
        torch = _torch()
        # only differentiable outputs participate (e.g. topk indices are
        # int tensors with no grad_fn); retain_graph so retained-tape
        # semantics (second backward over the same node) keep working
        pairs = [(t, to_torch(g).to(t.dtype))
                 for g, t in zip(ograds, self._tout)
                 if t.requires_grad and t.grad_fn is not None]
        if not pairs:
            return tuple(from_torch(torch.zeros_like(t))
                         for t in self._tin)
        outs, seeds = zip(*pairs)
        # differentiate only wrt the floating inputs (int indices have
        # requires_grad=False and make torch.autograd.grad raise)
        diff_idx = [i for i, t in enumerate(self._tin) if t.requires_grad]
        gdiff = torch.autograd.grad(outs, [self._tin[i] for i in diff_idx],
                                    seeds, allow_unused=True,
                                    retain_graph=True)
        gins = [None] * len(self._tin)
        for i, g in zip(diff_idx, gdiff):
            gins[i] = g
        return tuple(
            from_torch(g) if g is not None
            else from_torch(torch.zeros_like(t))
            for g, t in zip(gins, self._tin))


def function(torch_fn):
    """Wrap a torch callable as an NDArray operator (reference: torch.py
    generated mx.th.* functions). Differentiable through the tape."""

    def wrapped(*inputs, **kwargs):
        return _TorchFn(torch_fn, kwargs)(*inputs)

    wrapped.__name__ = getattr(torch_fn, "__name__", "torch_fn")
    return wrapped


class _TorchCriterion(Function):
    """One bridged (pred, label) loss call: like _TorchFn but the label is
    non-differentiable (reference: plugin/torch criterions)."""

    def __init__(self, criterion_fn, kwargs):
        super().__init__()
        self._fn = criterion_fn
        self._kwargs = kwargs
        self._tp = None
        self._tl = None
        self._tout = None

    def forward(self, p, lbl):
        torch = _torch()
        self._tp = to_torch(p).detach().clone().requires_grad_(True)
        self._tl = to_torch(lbl).detach()
        with torch.enable_grad():
            self._tout = self._fn(self._tp, self._tl, **self._kwargs)
        return from_torch(self._tout)

    def backward(self, ograd):
        torch = _torch()
        seed = to_torch(ograd).to(self._tout.dtype)
        (gp,) = torch.autograd.grad(self._tout, [self._tp], seed,
                                    retain_graph=True)
        zeros = torch.zeros_like(self._tl, dtype=self._tp.dtype) \
            if self._tl.dtype.is_floating_point \
            else torch.zeros(self._tl.shape)
        return from_torch(gp), from_torch(zeros)


def criterion(torch_criterion):
    """Wrap a torch loss module/callable as (pred, label) -> scalar loss
    (reference: plugin/torch criterions). Label is non-differentiable."""

    def wrapped(pred, label, **kwargs):
        return _TorchCriterion(torch_criterion, kwargs)(pred, label)

    return wrapped
