"""Image decode + augmentation pipeline.

Reference: python/mxnet/image/image.py (ImageIter + augmenter classes) and the
C++ pipeline src/io/iter_image_recordio_2.cc + image_aug_default.cc. Decode
and augmentation are host-side (PIL/numpy) exactly as the reference keeps them
on CPU (OpenCV); the batches stream to device asynchronously. Augmenter set
mirrors image_aug_default.cc: resize, random/center crop, mirror, HSL jitter,
mean/std normalize."""
from __future__ import annotations

import io as _io

import numpy as _np

from .base import MXNetError
from . import ndarray as nd
from .io import DataBatch, DataDesc, DataIter

__all__ = ["imdecode", "imencode", "imread", "imresize", "resize_short",
           "center_crop", "random_crop", "fixed_crop", "color_normalize",
           "Augmenter", "ResizeAug", "RandomCropAug", "CenterCropAug",
           "HorizontalFlipAug", "ColorNormalizeAug", "CastAug",
           "SaturationJitterAug", "HueJitterAug", "LightingAug", "RandomGrayAug",
           "CreateAugmenter", "ImageIter", "ImageRecordIterPy",
           "DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "DetResizeAug", "CreateMultiRandCropAugmenter",
           "CreateDetAugmenter", "ImageDetIter"]


def _pil():
    try:
        from PIL import Image

        return Image
    except ImportError:
        raise MXNetError("PIL is required for image decode in this build")


def imdecode(buf, flag=1, to_rgb=True, to_ndarray=True):
    """Decode an encoded image buffer -> HWC uint8 (reference: image.py imdecode)."""
    Image = _pil()
    img = Image.open(_io.BytesIO(bytes(buf)))
    img = img.convert("RGB" if flag else "L")
    arr = _np.asarray(img, dtype=_np.uint8)
    if not flag:
        arr = arr[:, :, None]
    if not to_rgb:
        arr = arr[:, :, ::-1]
    if to_ndarray:
        from . import base as _base

        if _base.HOST_ARRAY_MODE:   # DataLoader worker: stay numpy
            return arr
        return nd.array(arr, dtype="uint8")
    return arr


def imencode(img, quality=95, fmt=".jpg"):
    Image = _pil()
    if isinstance(img, nd.NDArray):
        img = img.asnumpy()
    img = _np.asarray(img, dtype=_np.uint8)
    if img.ndim == 3 and img.shape[2] == 1:
        img = img[:, :, 0]
    pil = Image.fromarray(img)
    out = _io.BytesIO()
    pil.save(out, format="JPEG" if fmt in (".jpg", ".jpeg") else "PNG",
             quality=quality)
    return out.getvalue()


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb)


def imresize(src, w, h, interp=1):
    """Resize HWC image (reference: image.py imresize). Container-preserving:
    numpy in -> numpy out (the DataLoader worker / HOST_ARRAY_MODE path must
    never touch jax), NDArray in -> NDArray out."""
    Image = _pil()
    was_nd = isinstance(src, nd.NDArray)
    arr = src.asnumpy() if was_nd else _np.asarray(src)
    squeeze = arr.ndim == 3 and arr.shape[2] == 1
    pil = Image.fromarray(arr[:, :, 0] if squeeze else arr.astype(_np.uint8))
    resample = Image.NEAREST if interp == 0 else Image.BILINEAR
    out = _np.asarray(pil.resize((w, h), resample))
    if squeeze:
        out = out[:, :, None]
    from . import base as _base

    if was_nd and not _base.HOST_ARRAY_MODE:
        return nd.array(out, dtype="uint8")
    return out


def resize_short(src, size, interp=2):
    h, w = src.shape[0], src.shape[1]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=2):
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = size
    x0 = _np.random.randint(0, max(w - new_w, 0) + 1)
    y0 = _np.random.randint(0, max(h - new_h, 0) + 1)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


class Augmenter:
    """Base augmenter (reference: image.py Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size if isinstance(size, tuple) else (size, size)
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size if isinstance(size, tuple) else (size, size)
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _np.random.rand() < self.p:
            if isinstance(src, nd.NDArray):
                return src.flip(axis=1)
            return src[:, ::-1]
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = nd.array(mean) if mean is not None else None
        self.std = nd.array(std) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.brightness, self.brightness)
        return (src * alpha).clip(0, 255)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.contrast, self.contrast)
        m = src.mean()
        gray = float(m.asscalar()) if hasattr(m, "asscalar") else float(m)
        return (src * alpha + gray * (1 - alpha)).clip(0, 255)


def _apply_np(src, fn):
    """Run fn on the numpy view of src, returning src's container type."""
    if isinstance(src, nd.NDArray):
        out = fn(src.asnumpy().astype(_np.float32))
        return nd.array(out.clip(0, 255), dtype=str(src.dtype)) \
            if str(src.dtype) == "uint8" else nd.array(out)
    out = fn(_np.asarray(src, _np.float32))
    return out.clip(0, 255).astype(src.dtype) \
        if _np.asarray(src).dtype == _np.uint8 else out


_GRAY_COEF = _np.array([0.299, 0.587, 0.114], _np.float32)


class SaturationJitterAug(Augmenter):
    """reference: image.py SaturationJitterAug — blend with per-pixel gray."""

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.saturation, self.saturation)

        def fn(a):
            gray = (a * _GRAY_COEF).sum(axis=2, keepdims=True)
            return a * alpha + gray * (1.0 - alpha)

        return _apply_np(src, fn)


class HueJitterAug(Augmenter):
    """reference: image.py HueJitterAug — YIQ-space hue rotation."""

    _TYIQ = _np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], _np.float32)
    _ITYIQ = _np.array([[1.0, 0.956, 0.621],
                        [1.0, -0.272, -0.647],
                        [1.0, -1.107, 1.705]], _np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        alpha = _np.random.uniform(-self.hue, self.hue)
        u = _np.cos(alpha * _np.pi)
        w = _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                       _np.float32)
        t = self._ITYIQ.dot(bt).dot(self._TYIQ).T

        def fn(a):
            return a.dot(t)

        return _apply_np(src, fn)


class LightingAug(Augmenter):
    """AlexNet-style PCA lighting noise (reference: image.py LightingAug)."""

    def __init__(self, alphastd, eigval=None, eigvec=None):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, _np.float32) if eigval is not None \
            else _np.array([55.46, 4.794, 1.148], _np.float32)
        self.eigvec = _np.asarray(eigvec, _np.float32) if eigvec is not None \
            else _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]], _np.float32)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = self.eigvec.dot(alpha * self.eigval).astype(_np.float32)

        def fn(a):
            return a + rgb

        return _apply_np(src, fn)


class RandomGrayAug(Augmenter):
    """reference: image.py RandomGrayAug — grayscale with probability p."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _np.random.rand() >= self.p:
            return src

        def fn(a):
            gray = (a * _GRAY_COEF).sum(axis=2, keepdims=True)
            return _np.broadcast_to(gray, a.shape).copy()

        return _apply_np(src, fn)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """Standard augmentation chain (reference: image.py CreateAugmenter,
    mirroring src/io/image_aug_default.cc order)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast:
        auglist.append(ContrastJitterAug(contrast))
    if saturation:
        auglist.append(SaturationJitterAug(saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise:
        auglist.append(LightingAug(pca_noise))
    if rand_gray:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and (isinstance(mean, _np.ndarray) or mean):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Python-side augmenting image iterator (reference: image.py ImageIter).
    Sources: .rec file (path_imgrec) or image list + root dir."""

    def __init__(self, batch_size, data_shape, label_width=1, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.data_name = data_name
        self.label_name = label_name
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in ("resize", "rand_crop",
                                                    "rand_mirror", "mean", "std")})
        self.record = None
        self.imglist = None
        if path_imgrec is not None:
            from . import recordio
            import os

            idx = os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.exists(idx):
                self.record = recordio.MXIndexedRecordIO(idx, path_imgrec, "r")
                self.seq = list(self.record.keys)
            else:
                # no index file: sequential read (reference image.py ImageIter
                # uses plain MXRecordIO with seq=None when path_imgidx is not
                # given; shuffle needs random access, hence the index)
                if shuffle:
                    raise MXNetError(
                        "ImageIter: shuffle requires an index file (%s) — "
                        "build one with tools/rec2idx.py" % idx)
                self.record = recordio.MXRecordIO(path_imgrec, "r")
                self.seq = None
        elif path_imglist is not None or imglist is not None:
            items = []
            if path_imglist is not None:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        label = [float(x) for x in parts[1:-1]]
                        items.append((parts[-1], label))
            else:
                for entry in imglist:
                    items.append((entry[-1], [float(x) for x in entry[:-1]]))
            self.imglist = items
            self.path_root = path_root
            self.seq = list(range(len(items)))
        else:
            raise MXNetError("ImageIter needs path_imgrec, path_imglist or imglist")
        self.shuffle = shuffle
        self.cur = 0
        if shuffle and self.seq is not None:
            _np.random.shuffle(self.seq)

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        self.cur = 0
        if self.seq is None:
            self.record.reset()  # sequential mode: rewind the stream
        elif self.shuffle:
            _np.random.shuffle(self.seq)

    def next_sample(self):
        if self.seq is None:  # sequential (un-indexed) record stream
            from . import recordio

            s = self.record.read()
            if s is None:
                raise StopIteration
            header, buf = recordio.unpack(s)
            return header.label, imdecode(buf)
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.record is not None:
            from . import recordio

            header, buf = recordio.unpack(self.record.read_idx(idx))
            label = header.label
            return label, imdecode(buf)
        fname, label = self.imglist[idx]
        import os

        return _np.asarray(label), imread(os.path.join(self.path_root, fname))

    def next(self):
        batch_data = []
        batch_label = []
        pad = 0
        for i in range(self.batch_size):
            try:
                label, img = self.next_sample()
            except StopIteration:
                if not batch_data:
                    raise
                pad = self.batch_size - len(batch_data)
                k = 0
                while len(batch_data) < self.batch_size:
                    # cycle: pad may exceed the collected count
                    batch_data.append(batch_data[k])
                    batch_label.append(batch_label[k])
                    k += 1
                break
            for aug in self.auglist:
                img = aug(img)
            arr = img.asnumpy() if isinstance(img, nd.NDArray) else img
            batch_data.append(_np.transpose(arr.astype(_np.float32), (2, 0, 1)))
            lab = _np.asarray(label, dtype=_np.float32).reshape(-1)
            batch_label.append(lab[0] if self.label_width == 1 else
                               lab[: self.label_width])
        data = nd.array(_np.stack(batch_data))
        label = nd.array(_np.stack(batch_label))
        return DataBatch(data=[data], label=[label], pad=pad)


class ImageRecordIterPy(ImageIter):
    """Threaded augmenting RecordIO iterator — the ImageRecordIter equivalent
    (reference: src/io/iter_image_recordio_2.cc:766 threaded parser +
    iter_prefetcher.h). preprocess_threads decode/augment in parallel;
    prefetch_buffer batches are staged ahead."""

    def __init__(self, path_imgrec, data_shape, batch_size, shuffle=False,
                 rand_crop=False, rand_mirror=False, mean=(0, 0, 0),
                 std=(1, 1, 1), resize=-1, label_width=1, preprocess_threads=4,
                 prefetch_buffer=4, **kwargs):
        mean_arr = _np.asarray(mean, _np.float32).reshape(1, 1, 3) \
            if any(m != 0 for m in mean) else None
        std_arr = _np.asarray(std, _np.float32).reshape(1, 1, 3) \
            if any(s != 1 for s in std) else None
        aug_list = CreateAugmenter(data_shape, resize=max(resize, 0),
                                   rand_crop=rand_crop, rand_mirror=rand_mirror,
                                   mean=mean_arr, std=std_arr)
        super().__init__(batch_size, data_shape, label_width=label_width,
                         path_imgrec=path_imgrec, shuffle=shuffle,
                         aug_list=aug_list)
        self._threads = max(1, preprocess_threads)
        self._buffer = max(1, prefetch_buffer)
        self._pool = None
        self._buf = None

    def _next_raw(self):
        """Sequential source stage (record readers are not thread-safe):
        one raw payload per sample, decode deferred to the pool."""
        if self.seq is None:  # sequential (un-indexed) record stream
            s = self.record.read()
            if s is None:
                raise StopIteration
            return ("rec", s)
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.record is not None:
            return ("rec", self.record.read_idx(idx))
        return ("img", self.imglist[idx])

    def _decode_raw(self, raw):
        """Parallel decode/augment stage — runs on the mxtpu-data-worker
        pool, preprocess_threads wide."""
        kind, payload = raw
        if kind == "rec":
            from . import recordio

            header, buf = recordio.unpack(payload)
            label, img = header.label, imdecode(buf)
        else:
            import os

            fname, label = payload
            label = _np.asarray(label)
            img = imread(os.path.join(self.path_root, fname))
        for aug in self.auglist:
            img = aug(img)
        arr = img.asnumpy() if isinstance(img, nd.NDArray) else img
        data = _np.transpose(arr.astype(_np.float32), (2, 0, 1))
        lab = _np.asarray(label, dtype=_np.float32).reshape(-1)
        return data, (lab[0] if self.label_width == 1 else
                      lab[: self.label_width])

    def _assemble(self):
        """Batch-assembly stage (PrefetchBuffer producer): collects
        pool-decoded samples — source order — into one DataBatch,
        pad-cycling the tail exactly like ImageIter.next."""
        batch_data = []
        batch_label = []
        pad = 0
        for _ in range(self.batch_size):
            try:
                data, lab = self._pool.get()
            except StopIteration:
                if not batch_data:
                    raise
                pad = self.batch_size - len(batch_data)
                k = 0
                while len(batch_data) < self.batch_size:
                    batch_data.append(batch_data[k])
                    batch_label.append(batch_label[k])
                    k += 1
                break
            batch_data.append(data)
            batch_label.append(lab)
        return DataBatch(data=[nd.array(_np.stack(batch_data))],
                         label=[nd.array(_np.stack(batch_label))], pad=pad)

    def _start(self):
        from .data.core import DecodePool, PrefetchBuffer

        self._pool = DecodePool(self._next_raw, self._decode_raw,
                                workers=self._threads,
                                depth=max(2, 2 * self._threads),
                                owner="ImageRecordIter.reset")
        self._buf = PrefetchBuffer(self._assemble, depth=self._buffer,
                                   name="mxtpu-image-prefetch",
                                   owner="ImageRecordIter.reset",
                                   src="image")

    def reset(self):
        if self._buf is not None:
            # stop + join the whole pipeline BEFORE touching reader state:
            # a live worker races super().reset()'s stream rewind
            # (sequential mode closes/reopens the file) and would feed
            # stale samples into the next epoch
            self._buf.close()
            self._pool.close()
            self._buf = None
            self._pool = None
        super().reset()

    def next(self):
        if self._buf is None:
            self._start()
        return self._buf.get()


# --------------------------------------------------------------------------
# Detection pipeline (reference: python/mxnet/image/detection.py + the C++
# detection-augmenting iterator src/io/iter_image_det_recordio.cc:509 /
# image_aug_default.cc det variant). Labels are normalized corner boxes:
# each row [cls, x1, y1, x2, y2, ...], coordinates in [0, 1].
# --------------------------------------------------------------------------

class DetAugmenter:
    """Detection augmenter base (reference: detection.py:39) — __call__
    takes and returns (image HWC uint8/float ndarray, label (N, 5+))."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only Augmenter for detection (reference:
    detection.py:65) — geometry-preserving augs (color, cast, normalize)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.__class__.__name__)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one augmenter from a list, or skip entirely
    (reference: detection.py:90)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or _np.random.random() < self.skip_prob:
            return src, label
        idx = _np.random.randint(len(self.aug_list))
        return self.aug_list[idx](src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image + boxes with probability p (reference: detection.py:126)."""

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _np.random.random() < self.p:
            src = _np.asarray(src)[:, ::-1]
            label = label.copy()
            label[:, 1], label[:, 3] = 1.0 - label[:, 3], 1.0 - label[:, 1].copy()
        return src, label


def _box_coverage(boxes, crop):
    """Fraction of each box's area inside crop (x1,y1,x2,y2 normalized)."""
    ix1 = _np.maximum(boxes[:, 0], crop[0])
    iy1 = _np.maximum(boxes[:, 1], crop[1])
    ix2 = _np.minimum(boxes[:, 2], crop[2])
    iy2 = _np.minimum(boxes[:, 3], crop[3])
    inter = _np.maximum(ix2 - ix1, 0) * _np.maximum(iy2 - iy1, 0)
    area = _np.maximum((boxes[:, 2] - boxes[:, 0]) *
                       (boxes[:, 3] - boxes[:, 1]), 1e-12)
    return inter / area


class DetRandomCropAug(DetAugmenter):
    """SSD-style constrained random crop (reference: detection.py:152): try
    up to max_attempts crops sampled in area/aspect range; accept when every
    kept object is covered >= min_object_covered; objects whose center falls
    outside or coverage < min_eject_coverage are ejected from the label."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _try_crop(self, label):
        area = _np.random.uniform(*self.area_range)
        ratio = _np.random.uniform(*self.aspect_ratio_range)
        w = min(_np.sqrt(area * ratio), 1.0)
        h = min(_np.sqrt(area / ratio), 1.0)
        x0 = _np.random.uniform(0, 1 - w)
        y0 = _np.random.uniform(0, 1 - h)
        crop = (x0, y0, x0 + w, y0 + h)
        boxes = label[:, 1:5]
        cov = _box_coverage(boxes, crop)
        cx = (boxes[:, 0] + boxes[:, 2]) / 2
        cy = (boxes[:, 1] + boxes[:, 3]) / 2
        center_in = (cx >= crop[0]) & (cx <= crop[2]) & \
                    (cy >= crop[1]) & (cy <= crop[3])
        keep = center_in & (cov >= self.min_eject_coverage)
        if not keep.any():
            return None
        if cov[keep].min() < self.min_object_covered:
            return None
        new = label[keep].copy()
        b = new[:, 1:5]
        b[:, (0, 2)] = (b[:, (0, 2)] - crop[0]) / max(crop[2] - crop[0], 1e-12)
        b[:, (1, 3)] = (b[:, (1, 3)] - crop[1]) / max(crop[3] - crop[1], 1e-12)
        new[:, 1:5] = _np.clip(b, 0.0, 1.0)
        return crop, new

    def __call__(self, src, label):
        for _ in range(self.max_attempts):
            got = self._try_crop(label)
            if got is None:
                continue
            crop, new_label = got
            src = _np.asarray(src)
            h, w = src.shape[:2]
            x1 = int(round(crop[0] * w))
            y1 = int(round(crop[1] * h))
            x2 = max(int(round(crop[2] * w)), x1 + 1)
            y2 = max(int(round(crop[3] * h)), y1 + 1)
            return src[y1:y2, x1:x2], new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Zoom-out: place the image on a larger canvas (reference:
    detection.py:323)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        src = _np.asarray(src)
        h, w = src.shape[:2]
        for _ in range(self.max_attempts):
            area = _np.random.uniform(*self.area_range)
            ratio = _np.random.uniform(*self.aspect_ratio_range)
            nw = _np.sqrt(area * ratio)
            nh = _np.sqrt(area / ratio)
            if nw < 1 or nh < 1:
                continue
            pw = int(round(w * nw))
            ph = int(round(h * nh))
            x0 = _np.random.randint(0, pw - w + 1)
            y0 = _np.random.randint(0, ph - h + 1)
            canvas = _np.empty((ph, pw, src.shape[2]), dtype=src.dtype)
            canvas[:] = _np.asarray(self.pad_val, dtype=src.dtype)
            canvas[y0:y0 + h, x0:x0 + w] = src
            label = label.copy()
            b = label[:, 1:5]
            b[:, (0, 2)] = (b[:, (0, 2)] * w + x0) / pw
            b[:, (1, 3)] = (b[:, (1, 3)] * h + y0) / ph
            label[:, 1:5] = b
            return canvas, label
        return src, label


class DetResizeAug(DetAugmenter):
    """Force resize to (w, h) — normalized boxes are unchanged."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src, label):
        img = imresize(src, self.size[0], self.size[1], self.interp)
        return _np.asarray(img), label


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0.0):
    """One DetRandomCropAug per listed constraint set, random-selected
    (reference: detection.py:417)."""
    def _as_list(v):
        return v if isinstance(v, (list, tuple)) and v and \
            isinstance(v[0], (list, tuple)) else [v]

    covered = min_object_covered if isinstance(min_object_covered,
                                               (list, tuple)) else \
        [min_object_covered]
    aspects = _as_list(aspect_ratio_range)
    areas = _as_list(area_range)
    ejects = min_eject_coverage if isinstance(min_eject_coverage,
                                              (list, tuple)) else \
        [min_eject_coverage]
    n = max(len(covered), len(aspects), len(areas), len(ejects))

    def _at(seq, i):
        return seq[i % len(seq)]

    augs = [DetRandomCropAug(_at(covered, i), _at(aspects, i), _at(areas, i),
                             _at(ejects, i), max_attempts) for i in range(n)]
    return DetRandomSelectAug(augs, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 3.0),
                       min_eject_coverage=0.3, max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Detection augmentation chain (reference: detection.py:482 — same
    option set/order: color jitter borrow, rand crop (prob), rand pad
    (prob), mirror, resize to data_shape, cast/normalize borrow)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if brightness:
        auglist.append(DetBorrowAug(BrightnessJitterAug(brightness)))
    if contrast:
        auglist.append(DetBorrowAug(ContrastJitterAug(contrast)))
    if saturation:
        auglist.append(DetBorrowAug(SaturationJitterAug(saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise:
        auglist.append(DetBorrowAug(LightingAug(pca_noise)))
    if rand_gray:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if rand_crop > 0:
        crop = CreateMultiRandCropAugmenter(
            min_object_covered, aspect_ratio_range,
            (area_range[0], min(area_range[1], 1.0)),
            min_eject_coverage, max_attempts, skip_prob=1 - rand_crop)
        auglist.append(crop)
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(area_range[1], 1.0)), max_attempts,
                              pad_val)
        auglist.append(DetRandomSelectAug([pad], skip_prob=1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetResizeAug((data_shape[2], data_shape[1]), inter_method))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and (isinstance(mean, _np.ndarray) or mean):
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator (reference: detection.py:624 ImageDetIter /
    C++ iter_image_det_recordio.cc). Labels use the im2rec detection
    format: [header_width, obj_width, (extras...), obj0..., obj1...] with
    each object [cls, x1, y1, x2, y2, ...] normalized; batches pad the
    object dimension with -1 rows to the dataset-wide max object count."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="label", **kwargs):
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, shuffle=shuffle,
                         aug_list=[], imglist=imglist, data_name=data_name,
                         label_name=label_name)
        self.auglist = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape, **kwargs)
        self.label_shape = self._estimate_label_shape()

    def _parse_label(self, label):
        """reference: detection.py _parse_label — strip the header, reshape
        to (N, obj_width), drop degenerate boxes."""
        raw = _np.asarray(label, dtype=_np.float32).ravel()
        if raw.size < 7:
            raise MXNetError("detection label too short: %d" % raw.size)
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if obj_width < 5 or (raw.size - header_width) % obj_width != 0:
            raise MXNetError("label shape %s inconsistent with obj width %d"
                             % (raw.shape, obj_width))
        out = raw[header_width:].reshape(-1, obj_width)
        valid = (out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2])
        if not valid.any():
            raise MXNetError("sample with no valid box")
        return out[valid]

    def _raw_labels(self):
        """Yield raw label vectors WITHOUT decoding images (the reference's
        label scan reads only recordio headers — decoding a whole COCO-scale
        .rec at construction would take minutes)."""
        if self.seq is None:
            # sequential (un-indexed) record: stream the headers once, then
            # rewind so iteration starts from record 0 (finally: the rewind
            # must happen even if a consumer stops early)
            from . import recordio

            try:
                while True:
                    s = self.record.read()
                    if s is None:
                        break
                    header, _ = recordio.unpack(s)
                    yield header.label
            finally:
                self.record.reset()
            return
        if self.record is not None:
            from . import recordio

            for idx in self.seq:
                header, _ = recordio.unpack(self.record.read_idx(idx))
                yield header.label
        else:
            for idx in self.seq:
                yield _np.asarray(self.imglist[idx][1], dtype=_np.float32)

    def _estimate_label_shape(self):
        max_count, width = 0, 5
        for label in self._raw_labels():
            try:
                lab = self._parse_label(label)
            except MXNetError:
                continue  # degenerate-only samples are skipped by next() too
            max_count = max(max_count, lab.shape[0])
            width = lab.shape[1]
        return (max_count, width)

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size,) + self.label_shape)]

    def reshape(self, data_shape=None, label_shape=None):
        if data_shape is not None:
            self.data_shape = tuple(data_shape)
        if label_shape is not None:
            self.label_shape = tuple(label_shape)

    def next(self):
        batch_data = []
        batch_label = []
        pad = 0
        while len(batch_data) < self.batch_size:
            try:
                label, img = self.next_sample()
            except StopIteration:
                if not batch_data:
                    raise
                pad = self.batch_size - len(batch_data)
                k = 0
                while len(batch_data) < self.batch_size:
                    batch_data.append(batch_data[k])
                    batch_label.append(batch_label[k])
                    k += 1
                break
            try:
                lab = self._parse_label(label)
            except MXNetError:
                continue
            img = _np.asarray(img)
            for aug in self.auglist:
                img, lab = aug(img, lab)
            arr = img.asnumpy() if isinstance(img, nd.NDArray) else \
                _np.asarray(img)
            batch_data.append(
                _np.transpose(arr.astype(_np.float32), (2, 0, 1)))
            padded = _np.full(self.label_shape, -1.0, _np.float32)
            n = min(lab.shape[0], self.label_shape[0])
            padded[:n, :lab.shape[1]] = lab[:n]
            batch_label.append(padded)
        data = nd.array(_np.stack(batch_data))
        label = nd.array(_np.stack(batch_label))
        return DataBatch(data=[data], label=[label], pad=pad)
