"""Image decode + augmentation pipeline.

Reference: python/mxnet/image/image.py (ImageIter + augmenter classes) and the
C++ pipeline src/io/iter_image_recordio_2.cc + image_aug_default.cc. Decode
and augmentation are host-side (PIL/numpy) exactly as the reference keeps them
on CPU (OpenCV); the batches stream to device asynchronously. Augmenter set
mirrors image_aug_default.cc: resize, random/center crop, mirror, HSL jitter,
mean/std normalize."""
from __future__ import annotations

import io as _io
import queue
import threading

import numpy as _np

from .base import MXNetError
from . import ndarray as nd
from .io import DataBatch, DataDesc, DataIter

__all__ = ["imdecode", "imencode", "imread", "imresize", "resize_short",
           "center_crop", "random_crop", "fixed_crop", "color_normalize",
           "Augmenter", "ResizeAug", "RandomCropAug", "CenterCropAug",
           "HorizontalFlipAug", "ColorNormalizeAug", "CastAug",
           "CreateAugmenter", "ImageIter", "ImageRecordIterPy"]


def _pil():
    try:
        from PIL import Image

        return Image
    except ImportError:
        raise MXNetError("PIL is required for image decode in this build")


def imdecode(buf, flag=1, to_rgb=True, to_ndarray=True):
    """Decode an encoded image buffer -> HWC uint8 (reference: image.py imdecode)."""
    Image = _pil()
    img = Image.open(_io.BytesIO(bytes(buf)))
    img = img.convert("RGB" if flag else "L")
    arr = _np.asarray(img, dtype=_np.uint8)
    if not flag:
        arr = arr[:, :, None]
    if not to_rgb:
        arr = arr[:, :, ::-1]
    if to_ndarray:
        return nd.array(arr, dtype="uint8")
    return arr


def imencode(img, quality=95, fmt=".jpg"):
    Image = _pil()
    if isinstance(img, nd.NDArray):
        img = img.asnumpy()
    img = _np.asarray(img, dtype=_np.uint8)
    if img.ndim == 3 and img.shape[2] == 1:
        img = img[:, :, 0]
    pil = Image.fromarray(img)
    out = _io.BytesIO()
    pil.save(out, format="JPEG" if fmt in (".jpg", ".jpeg") else "PNG",
             quality=quality)
    return out.getvalue()


def imread(filename, flag=1, to_rgb=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb)


def imresize(src, w, h, interp=1):
    """Resize HWC image (reference: image.py imresize)."""
    Image = _pil()
    arr = src.asnumpy() if isinstance(src, nd.NDArray) else _np.asarray(src)
    squeeze = arr.ndim == 3 and arr.shape[2] == 1
    pil = Image.fromarray(arr[:, :, 0] if squeeze else arr.astype(_np.uint8))
    resample = Image.NEAREST if interp == 0 else Image.BILINEAR
    out = _np.asarray(pil.resize((w, h), resample))
    if squeeze:
        out = out[:, :, None]
    return nd.array(out, dtype="uint8")


def resize_short(src, size, interp=2):
    h, w = src.shape[0], src.shape[1]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def random_crop(src, size, interp=2):
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = size
    x0 = _np.random.randint(0, max(w - new_w, 0) + 1)
    y0 = _np.random.randint(0, max(h - new_h, 0) + 1)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


class Augmenter:
    """Base augmenter (reference: image.py Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size if isinstance(size, tuple) else (size, size)
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size if isinstance(size, tuple) else (size, size)
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _np.random.rand() < self.p:
            return src.flip(axis=1)
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = nd.array(mean) if mean is not None else None
        self.std = nd.array(std) if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.brightness, self.brightness)
        return (src * alpha).clip(0, 255)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _np.random.uniform(-self.contrast, self.contrast)
        gray = float(src.mean().asscalar())
        return (src * alpha + gray * (1 - alpha)).clip(0, 255)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0, rand_gray=0,
                    inter_method=2):
    """Standard augmentation chain (reference: image.py CreateAugmenter,
    mirroring src/io/image_aug_default.cc order)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast:
        auglist.append(ContrastJitterAug(contrast))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and (isinstance(mean, _np.ndarray) or mean):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Python-side augmenting image iterator (reference: image.py ImageIter).
    Sources: .rec file (path_imgrec) or image list + root dir."""

    def __init__(self, batch_size, data_shape, label_width=1, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 **kwargs):
        super().__init__(batch_size)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.data_name = data_name
        self.label_name = label_name
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in ("resize", "rand_crop",
                                                    "rand_mirror", "mean", "std")})
        self.record = None
        self.imglist = None
        if path_imgrec is not None:
            from . import recordio
            import os

            idx = os.path.splitext(path_imgrec)[0] + ".idx"
            self.record = recordio.MXIndexedRecordIO(idx, path_imgrec, "r")
            self.seq = list(self.record.keys)
        elif path_imglist is not None or imglist is not None:
            items = []
            if path_imglist is not None:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        label = [float(x) for x in parts[1:-1]]
                        items.append((parts[-1], label))
            else:
                for entry in imglist:
                    items.append((entry[-1], [float(x) for x in entry[:-1]]))
            self.imglist = items
            self.path_root = path_root
            self.seq = list(range(len(items)))
        else:
            raise MXNetError("ImageIter needs path_imgrec, path_imglist or imglist")
        self.shuffle = shuffle
        self.cur = 0
        if shuffle:
            _np.random.shuffle(self.seq)

    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        self.cur = 0
        if self.shuffle:
            _np.random.shuffle(self.seq)

    def next_sample(self):
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.record is not None:
            from . import recordio

            header, buf = recordio.unpack(self.record.read_idx(idx))
            label = header.label
            return label, imdecode(buf)
        fname, label = self.imglist[idx]
        import os

        return _np.asarray(label), imread(os.path.join(self.path_root, fname))

    def next(self):
        batch_data = []
        batch_label = []
        pad = 0
        for i in range(self.batch_size):
            try:
                label, img = self.next_sample()
            except StopIteration:
                if not batch_data:
                    raise
                pad = self.batch_size - len(batch_data)
                batch_data.extend(batch_data[:pad])
                batch_label.extend(batch_label[:pad])
                break
            for aug in self.auglist:
                img = aug(img)
            arr = img.asnumpy() if isinstance(img, nd.NDArray) else img
            batch_data.append(_np.transpose(arr.astype(_np.float32), (2, 0, 1)))
            lab = _np.asarray(label, dtype=_np.float32).reshape(-1)
            batch_label.append(lab[0] if self.label_width == 1 else
                               lab[: self.label_width])
        data = nd.array(_np.stack(batch_data))
        label = nd.array(_np.stack(batch_label))
        return DataBatch(data=[data], label=[label], pad=pad)


class ImageRecordIterPy(ImageIter):
    """Threaded augmenting RecordIO iterator — the ImageRecordIter equivalent
    (reference: src/io/iter_image_recordio_2.cc:766 threaded parser +
    iter_prefetcher.h). preprocess_threads decode/augment in parallel;
    prefetch_buffer batches are staged ahead."""

    def __init__(self, path_imgrec, data_shape, batch_size, shuffle=False,
                 rand_crop=False, rand_mirror=False, mean=(0, 0, 0),
                 std=(1, 1, 1), resize=-1, label_width=1, preprocess_threads=4,
                 prefetch_buffer=4, **kwargs):
        mean_arr = _np.asarray(mean, _np.float32).reshape(1, 1, 3) \
            if any(m != 0 for m in mean) else None
        std_arr = _np.asarray(std, _np.float32).reshape(1, 1, 3) \
            if any(s != 1 for s in std) else None
        aug_list = CreateAugmenter(data_shape, resize=max(resize, 0),
                                   rand_crop=rand_crop, rand_mirror=rand_mirror,
                                   mean=mean_arr, std=std_arr)
        super().__init__(batch_size, data_shape, label_width=label_width,
                         path_imgrec=path_imgrec, shuffle=shuffle,
                         aug_list=aug_list)
        self._threads = max(1, preprocess_threads)
        self._buffer = max(1, prefetch_buffer)
        self._queue = None
        self._worker = None

    def _start(self):
        self._queue = queue.Queue(maxsize=self._buffer)

        def run():
            try:
                while True:
                    self._queue.put(ImageIter.next(self))
            except StopIteration:
                self._queue.put(None)
            except Exception as e:
                self._queue.put(e)

        self._worker = threading.Thread(target=run, daemon=True)
        self._worker.start()

    def reset(self):
        if self._worker is not None:
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
        super().reset()
        self._worker = None

    def next(self):
        if self._worker is None:
            self._start()
        item = self._queue.get()
        if item is None:
            self._worker = None
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item
