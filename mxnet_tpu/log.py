"""Logging utilities (reference: python/mxnet/log.py — a color/level
formatter and `get_logger` used across examples and tools)."""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger", "getLogger", "DEBUG", "INFO", "WARNING", "ERROR",
           "NOTSET"]

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
NOTSET = logging.NOTSET

PY3 = True


class _Formatter(logging.Formatter):
    """reference: log.py:37 — level-colored single-letter labels."""

    def __init__(self, colored=True):
        self.colored = colored
        super().__init__(datefmt="%m%d %H:%M:%S")

    def _get_color(self, level):
        if level >= ERROR:
            return "\x1b[31m"
        if level >= WARNING:
            return "\x1b[33m"
        return "\x1b[32m"

    def _get_label(self, level):
        if level == logging.CRITICAL:
            return "C"
        if level == ERROR:
            return "E"
        if level == WARNING:
            return "W"
        if level == INFO:
            return "I"
        if level == DEBUG:
            return "D"
        return "U"

    def format(self, record):
        fmt = ""
        if self.colored and sys.stderr.isatty():
            fmt += self._get_color(record.levelno)
        fmt += self._get_label(record.levelno)
        fmt += "%(asctime)s %(process)d %(pathname)s:%(funcName)s:%(lineno)d"
        if self.colored and sys.stderr.isatty():
            fmt += "\x1b[0m"
        fmt += " %(message)s"
        self._style._fmt = fmt
        return super().format(record)


def getLogger(name=None, filename=None, filemode=None, level=WARNING):
    """reference: log.py:80 (deprecated spelling, kept for parity)."""
    return get_logger(name, filename, filemode, level)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """A logger with the mxnet formatter attached once (reference:
    log.py:90)."""
    logger = logging.getLogger(name)
    if name is not None and not getattr(logger, "_init_done", False):
        logger._init_done = True
        if filename:
            mode = filemode if filemode else "a"
            hdlr = logging.FileHandler(filename, mode)
        else:
            hdlr = logging.StreamHandler()
        hdlr.setFormatter(_Formatter(colored=not filename))
        logger.addHandler(hdlr)
        logger.setLevel(level)
    return logger
