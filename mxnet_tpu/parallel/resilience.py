"""Fault-tolerance layer: crash-consistent checkpoints, auto-resume, and
deterministic fault injection.

The reference framework inherited node-failure semantics from ps-lite (PAPER
§1 layer map: a dead worker was detected by the scheduler's heartbeat and the
job continued or restarted from the server-side parameter copies). The XLA
collectives replacement (SURVEY §5.8) is a static synchronous group — one
dead rank stalls every collective — so recovery is restructured TPU-natively
around three pieces (docs/fault_tolerance.md):

  * the elastic launcher (tools/launch.py --max-restarts) tears the whole
    group down on first failure and respawns a fresh generation on a fresh
    rendezvous port;
  * `CheckpointManager` (this module) keeps periodic crash-consistent
    checkpoints — write-temp + fsync + atomic rename, per-file checksums,
    keep-last-N retention — capturing params, optimizer/Trainer state, the
    RNG key chain and the step cursor;
  * auto-resume (`CheckpointManager.restore`, `module.fit(resume='auto')`)
    makes the new generation continue from the last COMPLETE checkpoint
    instead of step 0.

`MXTPU_FAULT_INJECT` gives tests a deterministic way to kill a worker at an
exact step boundary and prove the restart→resume→converge path end to end
(tests/test_resilience.py).

On top of that PR-2 base this module carries the elastic-resilience layer
(docs/fault_tolerance.md §Preemption & elastic resume):

  * **async checkpointing** — `save_async` / `save_sharded_async` push
    serialize+fsync+atomic-rename onto ONE named background writer thread
    (`mxtpu-ckpt-writer`, bounded queue, at-most-one in flight) so the
    fused training step only ever pays the host snapshot;
  * a **per-rank sharded format** — every rank stages its own
    `shard-r<rank>.bin`, rank 0 publishes a manifest (`meta.json`, still
    written last) carrying the `parallel.mesh.mesh_fingerprint` topology —
    replacing gather-to-rank0;
  * **graceful preemption** — `install_preemption_handler` +
    `maybe_preempt_exit` turn SIGTERM into finish-step → emergency
    checkpoint inside `MXTPU_PREEMPT_GRACE_S` → exit
    `MXTPU_PREEMPT_EXIT_CODE`, which tools/launch.py restarts for free;
  * **elastic resume** — `restore_sharded` reads the manifest and, when
    the new generation's topology/world size differs, hands the loader
    EVERY shard so the trainer reshards onto the new mesh (N→M ranks).
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
import threading
import time
import zlib

from .. import env as _env
from ..base import MXNetError, atomic_writer, _fsync_dir
from .. import telemetry

__all__ = ["CheckpointManager", "maybe_inject_fault",
           "maybe_inject_serving_fault", "maybe_inject_load_surge",
           "fault_spec", "restart_generation",
           "install_preemption_handler", "preemption_requested",
           "maybe_preempt_exit", "preempt_exit_code", "preempt_grace_s"]

_LOG = logging.getLogger("mxnet_tpu.resilience")

CKPT_FORMAT_VERSION = 1
_META = "meta.json"
_PARAMS = "data.params"
_STATES = "trainer.states"
_SHARD = "shard-r%05d.bin"
_SHARD_OK = "shard-r%05d.ok.json"
_WRITER_THREAD = "mxtpu-ckpt-writer"


def restart_generation():
    """Which supervision generation this process belongs to (0 = first
    launch). tools/launch.py exports MXTPU_RESTART_GENERATION on every
    worker it respawns after a failure."""
    return _env.get("MXTPU_RESTART_GENERATION")


def _current_rank():
    """Rank from the launcher env protocol, without touching jax (the fault
    hook runs on every step; importing/initializing jax here would be both
    heavy and wrong before init_process_group)."""
    for name in ("MXTPU_PROCESS_ID", "DMLC_WORKER_ID", "OMPI_COMM_WORLD_RANK",
                 "PMI_RANK", "SLURM_PROCID"):
        v = _env.raw(name) if name.startswith("MXTPU_") \
            else os.environ.get(name)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


# --------------------------------------------------------------------------
# Async checkpoint writer
# --------------------------------------------------------------------------

class _AsyncCkptWriter:
    """Background checkpoint serializer: ONE named daemon thread
    (`mxtpu-ckpt-writer`), a bounded queue of at-most-one pending job
    behind the in-flight one, and honest backpressure — submit() blocks
    when the slot is taken, so a slow disk degrades checkpoint cadence
    instead of growing an unbounded backlog of host snapshots. The thread
    is daemon (the conftest leaked-thread report counts live non-daemon
    threads) AND explicitly joinable via close(); a failed async save is
    captured and re-raised on the next flush()/submit() so it can never
    pass silently."""

    def __init__(self):
        self._cv = threading.Condition()
        self._job = None            # (fn, step) queued, not yet started
        self._busy = False          # a job is executing right now
        self._closed = False
        self._error = None          # first exception a job raised
        self._submitted_step = None
        self._completed_step = None
        self._thread = threading.Thread(target=self._run,
                                        name=_WRITER_THREAD, daemon=True)
        self._thread.start()

    def submit(self, fn, step):
        with self._cv:
            if self._closed:
                raise MXNetError("async checkpoint writer is closed")
            self._raise_error_locked()
            while self._job is not None:   # at-most-one pending: block
                self._cv.wait()
                self._raise_error_locked()
            self._job = (fn, int(step))
            self._submitted_step = int(step)
            self._cv.notify_all()
        self._export_gauges()

    def flush(self, timeout=None):
        """Block until everything submitted so far is durably written;
        False on timeout. Re-raises the first error an async save hit."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._job is not None or self._busy:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
            self._raise_error_locked()
        return True

    def close(self, timeout=5.0):
        """flush + join: checkpoint-heavy tests end with the writer thread
        actually gone, not merely daemonized."""
        try:
            ok = self.flush(timeout)
        finally:
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            self._thread.join(timeout)
        return ok and not self._thread.is_alive()

    def _raise_error_locked(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def _run(self):
        while True:
            with self._cv:
                while self._job is None and not self._closed:
                    self._cv.wait()
                if self._job is None:
                    return  # closed and drained
                fn, step = self._job
                self._job = None
                self._busy = True
                self._cv.notify_all()
            try:
                fn()
            except BaseException as e:
                with self._cv:
                    self._error = e if isinstance(e, Exception) else \
                        MXNetError("async checkpoint writer died: %r" % (e,))
            finally:
                with self._cv:
                    self._busy = False
                    self._completed_step = step
                    self._cv.notify_all()
                self._export_gauges()
                telemetry.record_event("ckpt_async_complete", step=step)

    def _export_gauges(self):
        with self._cv:
            sub = self._submitted_step or 0
            done = self._completed_step or 0
            pending = (1 if self._job is not None else 0) + \
                (1 if self._busy else 0)
        # how far the newest DURABLE checkpoint trails the newest snapshot
        telemetry.gauge("mxtpu_checkpoint_async_lag_steps").set(
            max(0, sub - done))
        telemetry.gauge("mxtpu_checkpoint_async_pending").set(pending)


# --------------------------------------------------------------------------
# CheckpointManager
# --------------------------------------------------------------------------

class CheckpointManager:
    """Periodic crash-consistent checkpoints with discovery and retention.

    Layout: one directory per step under `directory`:

        <directory>/<prefix>-00000006/
            data.params     params (nd.save npz; optional)
            trainer.states  optimizer/Trainer state blob (optional)
            meta.json       written LAST: version, step, crc32 per file,
                            RNG snapshot, user metadata

    Write protocol (crash-consistent): everything is staged into a hidden
    same-filesystem temp directory, every file is fsynced, meta.json is
    written last, then ONE atomic rename publishes the step. A process
    killed at any point leaves either no trace (stale temp, cleaned up on
    the next save) or a complete verified checkpoint — never a torn one.
    `latest()` verifies checksums and silently skips incomplete/corrupt
    steps, so auto-resume always lands on the newest COMPLETE state.

    The save/restore payloads are writer/loader callables so every training
    surface wires in thinly:

        gluon:   mgr.save(step, save_params=net.save_parameters,
                          save_states=trainer.save_states)
        module:  handled by module.fit(checkpoint_dir=..., resume='auto')
        mesh:    mgr.save(step, save_states=distributed_trainer.save_states,
                          save_params=...)

    Multi-process note: checkpoints are group-consistent because dist_sync
    training keeps replicas identical; by convention only rank 0 writes
    (`rank0_only=True`) and every rank restores from the shared directory.
    """

    def __init__(self, directory, keep_last=3, prefix="ckpt", save_every=None,
                 rank0_only=True):
        self._dir = os.path.abspath(os.fspath(directory))
        if keep_last is not None and keep_last < 1:
            raise MXNetError("keep_last must be >= 1 (or None for unlimited)")
        self._keep_last = keep_last
        self._prefix = prefix
        self._save_every = save_every
        self._rank0_only = rank0_only
        self._async_writer = None  # lazily started on first *_async save
        os.makedirs(self._dir, exist_ok=True)

    # -- naming ------------------------------------------------------------
    @property
    def directory(self):
        return self._dir

    def step_path(self, step):
        return os.path.join(self._dir, "%s-%08d" % (self._prefix, int(step)))

    def _step_of(self, name):
        tag = self._prefix + "-"
        if not name.startswith(tag):
            return None
        try:
            return int(name[len(tag):])
        except ValueError:
            return None

    def _all_steps(self):
        try:
            names = os.listdir(self._dir)
        except FileNotFoundError:
            return []
        steps = [(s, os.path.join(self._dir, n)) for n in names
                 for s in [self._step_of(n)] if s is not None]
        return sorted(steps, reverse=True)

    # -- save --------------------------------------------------------------
    def maybe_save(self, step, **kwargs):
        """save() when `step` hits the manager's save_every period."""
        if self._save_every is None or step % self._save_every != 0:
            return None
        return self.save(step, **kwargs)

    def save(self, step, save_params=None, save_states=None, meta=None):
        """Write one crash-consistent checkpoint; returns its path (or None
        on non-zero ranks when rank0_only)."""
        if self._rank0_only and _current_rank() != 0:
            return None
        t0 = time.perf_counter()
        self._sweep_stale_tmp()
        tmp = tempfile.mkdtemp(dir=self._dir,
                               prefix=".tmp-%s-%08d-" % (self._prefix, step))
        try:
            files = {}
            if save_params is not None:
                save_params(os.path.join(tmp, _PARAMS))
                files[_PARAMS] = None
            if save_states is not None:
                save_states(os.path.join(tmp, _STATES))
                files[_STATES] = None
            for name in files:
                files[name] = self._fsync_and_crc(os.path.join(tmp, name))
            # chaos hook: payload staged, meta.json not yet written — the
            # exact window where a torn write would surface if the format
            # were not crash-consistent
            _maybe_kill_during_ckpt(step)
            from .. import random as _random

            header = {
                "version": CKPT_FORMAT_VERSION,
                "step": int(step),
                "time": time.time(),
                "crc32": files,
                "rng": _random.get_state(),
                "meta": dict(meta or {}),
            }
            with atomic_writer(os.path.join(tmp, _META), "w") as f:
                json.dump(header, f, indent=1)
            _fsync_dir(tmp)
            final = self.step_path(step)
            if os.path.exists(final):
                # same step saved twice (e.g. resumed run re-reaches a saved
                # step): the existing dir is superseded, replace it
                shutil.rmtree(final)
            os.replace(tmp, final)
            tmp = None
            _fsync_dir(self._dir)
        finally:
            if tmp is not None:
                shutil.rmtree(tmp, ignore_errors=True)
        # measure BEFORE _retain(): retention may legally delete the step
        # just published (pinned-resume past newer checkpoints), and a
        # successful save must never crash on its own bookkeeping
        try:
            nbytes = sum(os.path.getsize(os.path.join(final, n))
                         for n in os.listdir(final))
        except OSError:
            nbytes = 0
        self._retain()
        seconds = time.perf_counter() - t0
        # inside a traced step/run, the checkpoint becomes a child span
        telemetry.tracing.emit_span(
            "train.checkpoint", time.time() - seconds, seconds,
            telemetry.tracing.current(), component="train",
            attrs={"step": int(step), "bytes": nbytes})
        telemetry.histogram("mxtpu_checkpoint_seconds",
                            {"what": "save"}).observe(seconds)
        telemetry.counter("mxtpu_checkpoint_bytes_total",
                          {"what": "save"}).inc(nbytes)
        self._observe_stall(seconds)
        telemetry.record_event("checkpoint_save", step=int(step),
                               seconds=round(seconds, 4), bytes=nbytes,
                               path=final)
        return final

    @staticmethod
    def _observe_stall(seconds):
        """Training-thread stall attribution: a save running on the
        background writer costs the training loop nothing, so only
        non-writer-thread saves land in the sync-stall series."""
        if threading.current_thread().name != _WRITER_THREAD:
            telemetry.histogram("mxtpu_checkpoint_stall_seconds",
                                {"mode": "sync"}).observe(seconds)
            telemetry.goodput.add("checkpoint_stall", seconds)

    def _fsync_and_crc(self, path):
        crc = 0
        with open(path, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
            os.fsync(f.fileno())
        return crc & 0xFFFFFFFF

    def _sweep_stale_tmp(self):
        """Remove staging dirs a previous (killed) generation left behind.
        Shard staging dirs are generation-tagged so a dead generation's
        half-staged shards (with their stale `.ok` markers) can never
        satisfy the current generation's manifest wait — only FOREIGN
        generations' dirs are swept; the current one may be in flight on
        the async writer."""
        gen_tag = "-g%d" % restart_generation()
        for name in os.listdir(self._dir):
            if name.startswith(".tmp-%s-" % self._prefix):
                shutil.rmtree(os.path.join(self._dir, name),
                              ignore_errors=True)
            elif (name.startswith(".shards-%s-" % self._prefix)
                    and not name.endswith(gen_tag)):
                shutil.rmtree(os.path.join(self._dir, name),
                              ignore_errors=True)

    def _retain(self):
        if self._keep_last is None:
            return
        kept = 0
        for step, path in self._all_steps():
            # cheap completeness check only (meta.json parses): a published
            # dir is complete by construction (meta written last + atomic
            # rename), and full CRC verification on every save would re-read
            # keep_last whole checkpoints per step — latest()/restore()
            # still checksum before anything is trusted
            if kept < self._keep_last and self._meta_ok(path):
                kept += 1
                continue
            # incomplete entries don't count toward the quota but are only
            # removed once a newer complete checkpoint protects the history
            if kept > 0:
                shutil.rmtree(path, ignore_errors=True)

    def _meta_ok(self, path):
        try:
            with open(os.path.join(path, _META)) as f:
                return json.load(f).get("version") == CKPT_FORMAT_VERSION
        except (OSError, ValueError):
            return False

    # -- discovery / verification ------------------------------------------
    def verify(self, path):
        """True iff `path` is a complete checkpoint whose files match the
        checksums recorded at save time."""
        return self._verify_reason(path) is None

    def _verify_reason(self, path):
        meta_path = os.path.join(path, _META)
        try:
            with open(meta_path) as f:
                header = json.load(f)
        except (OSError, ValueError) as e:
            return "unreadable meta.json (%s)" % (e,)
        if header.get("version") != CKPT_FORMAT_VERSION:
            return "format version %r != %d" % (header.get("version"),
                                                CKPT_FORMAT_VERSION)
        for name, crc in (header.get("crc32") or {}).items():
            fp = os.path.join(path, name)
            try:
                got = self._fsync_less_crc(fp)
            except OSError as e:
                return "missing payload %s (%s)" % (name, e)
            if got != crc:
                return "checksum mismatch on %s (stored %d, got %d)" % (
                    name, crc, got)
        return None

    @staticmethod
    def _fsync_less_crc(path):
        crc = 0
        with open(path, "rb") as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
        return crc & 0xFFFFFFFF

    def latest(self):
        """(step, path) of the newest COMPLETE checkpoint, or None. Corrupt
        or partially-written steps are skipped with a warning — the caller
        resumes from the last state that verifies."""
        for step, path in self._all_steps():
            reason = self._verify_reason(path)
            if reason is None:
                return step, path
            _LOG.warning("skipping corrupt checkpoint %s: %s", path, reason)
        return None

    def read_meta(self, path):
        with open(os.path.join(path, _META)) as f:
            return json.load(f)

    # -- restore -----------------------------------------------------------
    def restore(self, load_params=None, load_states=None, step=None,
                restore_rng=True):
        """Load a checkpoint (default: latest complete one) through the
        caller's loaders; returns the saved header dict (step/meta/rng) or
        None when no complete checkpoint exists. An EXPLICITLY requested
        step that fails verification raises MXNetError instead of silently
        falling back."""
        t0 = time.perf_counter()
        if step is None:
            found = self.latest()
            if found is None:
                return None
            step, path = found
        else:
            path = self.step_path(step)
            reason = self._verify_reason(path)
            if reason is not None:
                raise MXNetError(
                    "checkpoint %s failed verification: %s" % (path, reason))
        header = self.read_meta(path)
        if header.get("format") == "sharded" and (load_params is not None
                                                 or load_states is not None):
            raise MXNetError(
                "checkpoint %s is sharded (per-rank shards + manifest); "
                "load it with restore_sharded()" % path)
        files = header.get("crc32") or {}
        if load_params is not None and _PARAMS in files:
            load_params(os.path.join(path, _PARAMS))
        if load_states is not None and _STATES in files:
            load_states(os.path.join(path, _STATES))
        if restore_rng and header.get("rng"):
            from .. import random as _random

            _random.set_state(header["rng"])
        seconds = time.perf_counter() - t0
        nbytes = sum(os.path.getsize(os.path.join(path, n)) for n in files
                     if os.path.exists(os.path.join(path, n)))
        telemetry.histogram("mxtpu_checkpoint_seconds",
                            {"what": "restore"}).observe(seconds)
        telemetry.counter("mxtpu_checkpoint_bytes_total",
                          {"what": "restore"}).inc(nbytes)
        telemetry.record_event("checkpoint_restore", step=int(step),
                               seconds=round(seconds, 4), bytes=nbytes,
                               generation=restart_generation())
        return header

    # -- async façade ------------------------------------------------------
    @staticmethod
    def _async_on():
        return bool(_env.get("MXTPU_CKPT_ASYNC"))

    def _writer(self):
        w = self._async_writer
        if w is None or not w._thread.is_alive():
            w = self._async_writer = _AsyncCkptWriter()
        return w

    def flush(self, timeout=None):
        """Wait until any async save submitted so far is durable. No-op
        (True) when nothing is pending; False on timeout; re-raises the
        first error a background save hit."""
        w = self._async_writer
        return True if w is None else w.flush(timeout)

    def close(self, timeout=5.0):
        """flush + join the background writer thread (idempotent)."""
        w, self._async_writer = self._async_writer, None
        return True if w is None else w.close(timeout)

    def maybe_save_async(self, step, **kwargs):
        """save_async() when `step` hits the manager's save_every period."""
        if self._save_every is None or step % self._save_every != 0:
            return None
        return self.save_async(step, **kwargs)

    def save_async(self, step, snapshot_params=None, snapshot_states=None,
                   meta=None):
        """Asynchronous save(): the `snapshot_*` callables run NOW on the
        calling thread — they must capture a host-side copy of the live
        state and return the save()-style writer callable — then
        serialize+fsync+atomic-rename runs on the named background writer
        (`mxtpu-ckpt-writer`). The training thread's only stall is
        snapshot+submit. MXTPU_CKPT_ASYNC=0 degrades to a plain
        synchronous save() with the same payload (the escape hatch when
        the extra host copy is the scarcer resource)."""
        if self._rank0_only and _current_rank() != 0:
            return None
        t0 = time.perf_counter()
        wp = snapshot_params() if snapshot_params is not None else None
        ws = snapshot_states() if snapshot_states is not None else None
        if not self._async_on():
            return self.save(step, save_params=wp, save_states=ws, meta=meta)
        self._writer().submit(
            lambda: self.save(step, save_params=wp, save_states=ws,
                              meta=meta), step)
        stall = time.perf_counter() - t0
        telemetry.histogram("mxtpu_checkpoint_stall_seconds",
                            {"mode": "async"}).observe(stall)
        telemetry.goodput.add("checkpoint_stall", stall)
        telemetry.record_event("ckpt_async_submit", step=int(step),
                               stall_s=round(stall, 5))
        return None

    # -- per-rank sharded format -------------------------------------------
    def _shard_stage_dir(self, step):
        return os.path.join(self._dir, ".shards-%s-%08d-g%d" % (
            self._prefix, int(step), restart_generation()))

    def save_sharded(self, step, payload, rank=0, world_size=1,
                     topology=None, meta=None, shard_timeout=None):
        """Per-rank sharded checkpoint (replaces gather-to-rank0): EVERY
        rank calls this with its own picklable `payload`. Each rank stages
        `shard-r<rank>.bin` + an `.ok` marker into a shared
        generation-tagged staging dir; rank 0 then waits (up to
        MXTPU_CKPT_SHARD_TIMEOUT_S) for all `world_size` shards and
        publishes the manifest — `meta.json` written LAST, one atomic
        rename — so the PR-2 crash-consistency discipline, `latest()`
        discovery, retention and corruption-skip all work unchanged on
        sharded steps. `topology` (parallel.mesh.mesh_fingerprint) rides
        the manifest so restore_sharded() can detect an elastic resume.
        Returns the published path on rank 0, None elsewhere.
        `rank0_only` does not apply: the sharded format needs every rank's
        payload by construction."""
        import pickle

        t0 = time.perf_counter()
        self._sweep_stale_tmp()
        stage = self._shard_stage_dir(step)
        os.makedirs(stage, exist_ok=True)
        name = _SHARD % int(rank)
        with atomic_writer(os.path.join(stage, name), "wb") as f:
            pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
        crc = self._fsync_and_crc(os.path.join(stage, name))
        # chaos hook: shard staged, manifest absent — the torn window
        _maybe_kill_during_ckpt(step)
        with atomic_writer(os.path.join(stage, _SHARD_OK % int(rank)),
                           "w") as f:
            json.dump({"rank": int(rank), "crc32": crc}, f)
        if int(rank) != 0:
            self._observe_stall(time.perf_counter() - t0)
            return None
        timeout = shard_timeout if shard_timeout is not None \
            else _env.get("MXTPU_CKPT_SHARD_TIMEOUT_S")
        deadline = time.monotonic() + timeout
        files = {}
        for r in range(int(world_size)):
            okp = os.path.join(stage, _SHARD_OK % r)
            while not os.path.exists(okp):
                if time.monotonic() >= deadline:
                    raise MXNetError(
                        "sharded checkpoint step %d: shard %d/%d never "
                        "arrived within %.0fs (%s) — a peer likely died "
                        "mid-save; the staging dir stays invisible to "
                        "latest()" % (step, r, world_size, timeout, stage))
                time.sleep(0.02)
            with open(okp) as f:
                files[_SHARD % r] = json.load(f)["crc32"]
        from .. import random as _random

        header = {
            "version": CKPT_FORMAT_VERSION,
            "format": "sharded",
            "step": int(step),
            "time": time.time(),
            "crc32": files,
            "shards": int(world_size),
            "world_size": int(world_size),
            "topology": topology,
            "rng": _random.get_state(),
            "meta": dict(meta or {}),
        }
        with atomic_writer(os.path.join(stage, _META), "w") as f:
            json.dump(header, f, indent=1)
        # the manifest's crc32 map is now authoritative; drop the markers
        for r in range(int(world_size)):
            try:
                os.unlink(os.path.join(stage, _SHARD_OK % r))
            except OSError:
                pass
        _fsync_dir(stage)
        final = self.step_path(step)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(stage, final)
        _fsync_dir(self._dir)
        try:
            nbytes = sum(os.path.getsize(os.path.join(final, n))
                         for n in os.listdir(final))
        except OSError:
            nbytes = 0
        self._retain()
        seconds = time.perf_counter() - t0
        telemetry.tracing.emit_span(
            "train.checkpoint", time.time() - seconds, seconds,
            telemetry.tracing.current(), component="train",
            attrs={"step": int(step), "bytes": nbytes, "sharded": True})
        telemetry.histogram("mxtpu_checkpoint_seconds",
                            {"what": "save"}).observe(seconds)
        telemetry.counter("mxtpu_checkpoint_bytes_total",
                          {"what": "save"}).inc(nbytes)
        self._observe_stall(seconds)
        telemetry.record_event("checkpoint_save", step=int(step),
                               seconds=round(seconds, 4), bytes=nbytes,
                               path=final, sharded=True,
                               shards=int(world_size))
        return final

    def save_sharded_async(self, step, payload, rank=0, world_size=1,
                           topology=None, meta=None):
        """save_sharded() with staging+publish on the background writer:
        the caller already paid the only synchronous cost (snapshotting
        `payload` to host), and rank 0's wait for peer shards happens on
        the writer thread too, so a straggler rank never stalls training
        anywhere else. MXTPU_CKPT_ASYNC=0 degrades to the sync path."""
        t0 = time.perf_counter()
        if not self._async_on():
            return self.save_sharded(step, payload, rank=rank,
                                     world_size=world_size,
                                     topology=topology, meta=meta)
        self._writer().submit(
            lambda: self.save_sharded(step, payload, rank=rank,
                                      world_size=world_size,
                                      topology=topology, meta=meta), step)
        stall = time.perf_counter() - t0
        telemetry.histogram("mxtpu_checkpoint_stall_seconds",
                            {"mode": "async"}).observe(stall)
        telemetry.goodput.add("checkpoint_stall", stall)
        telemetry.record_event("ckpt_async_submit", step=int(step),
                               stall_s=round(stall, 5), sharded=True)
        return None

    def restore_sharded(self, load_shards, step=None, rank=0, world_size=1,
                        topology=None, restore_rng=True):
        """Restore a sharded checkpoint through ``load_shards(payloads,
        header)``, where ``payloads`` maps saved shard rank → unpickled
        payload.

        Fast path — the manifest's topology equals this run's `topology`
        AND its shard count equals `world_size`: each rank reads ONLY its
        own shard. Elastic path (any mismatch): EVERY shard is read and
        handed to the loader, which reassembles the global state and
        reshards it onto the new mesh (N→M ranks, both directions). The
        caller's compile key carries the same topology fingerprint, so an
        elastic resume honestly misses the executable cache exactly once.
        Returns the manifest header, or None when no complete checkpoint
        exists; an explicitly requested `step` that fails verification
        raises."""
        import pickle

        t0 = time.perf_counter()
        if step is None:
            found = self.latest()
            if found is None:
                return None
            step, path = found
        else:
            path = self.step_path(step)
            reason = self._verify_reason(path)
            if reason is not None:
                raise MXNetError(
                    "checkpoint %s failed verification: %s" % (path, reason))
        header = self.read_meta(path)
        if header.get("format") != "sharded":
            raise MXNetError(
                "checkpoint %s is not sharded — restore() is the loader "
                "for rank0-only checkpoints" % path)
        shards = int(header.get("shards") or 0)
        elastic = not (header.get("topology") == topology
                       and shards == int(world_size))
        ranks = range(shards) if elastic else [int(rank)]
        payloads = {}
        for r in ranks:
            with open(os.path.join(path, _SHARD % r), "rb") as f:
                payloads[r] = pickle.load(f)
        if elastic:
            _LOG.warning(
                "elastic resume: checkpoint step %d saved on %r (%d "
                "shard(s)) -> restoring onto %r (world %d); resharding",
                step, header.get("topology"), shards, topology,
                int(world_size))
            telemetry.record_event(
                "ckpt_reshard", step=int(step), from_shards=shards,
                to_world=int(world_size),
                from_topology=header.get("topology"), to_topology=topology)
        load_shards(payloads, header)
        if restore_rng and header.get("rng"):
            from .. import random as _random

            _random.set_state(header["rng"])
        seconds = time.perf_counter() - t0
        files = header.get("crc32") or {}
        nbytes = sum(os.path.getsize(os.path.join(path, n)) for n in files
                     if os.path.exists(os.path.join(path, n)))
        telemetry.histogram("mxtpu_checkpoint_seconds",
                            {"what": "restore"}).observe(seconds)
        telemetry.counter("mxtpu_checkpoint_bytes_total",
                          {"what": "restore"}).inc(nbytes)
        telemetry.record_event("checkpoint_restore", step=int(step),
                               seconds=round(seconds, 4), bytes=nbytes,
                               generation=restart_generation(),
                               sharded=True, elastic=elastic)
        return header


# --------------------------------------------------------------------------
# Fault injection (MXTPU_FAULT_INJECT)
# --------------------------------------------------------------------------
#
# Grammar: semicolon-separated entries, each `action@cond,cond,...` with
# conditions `key=value`:
#
#   MXTPU_FAULT_INJECT="kill@step=7,rank=1"         SIGKILL-equivalent exit
#                                                   of rank 1 at step 7
#   MXTPU_FAULT_INJECT="exc@step=3"                 raise MXNetError
#   MXTPU_FAULT_INJECT="hang@step=5,rank=1"         park the rank forever at
#                                                   the step boundary (models
#                                                   a wedged collective /
#                                                   stuck host callback; the
#                                                   telemetry watchdog +
#                                                   flight recorder are the
#                                                   intended detectors)
#   MXTPU_FAULT_INJECT="corrupt_ckpt@step=5,dir=/tmp/ck"
#                                                   garble the newest
#                                                   checkpoint's params file
#   MXTPU_FAULT_INJECT="preempt@step=7,rank=1,grace=30"
#                                                   deliver SIGTERM to the
#                                                   rank at the step
#                                                   boundary (the cloud
#                                                   preemption notice);
#                                                   grace= overrides
#                                                   MXTPU_PREEMPT_GRACE_S.
#                                                   The worker finishes the
#                                                   step, emergency-
#                                                   checkpoints and exits
#                                                   MXTPU_PREEMPT_EXIT_CODE
#   MXTPU_FAULT_INJECT="kill_during_ckpt@step=4,rank=0"
#                                                   die MID-SAVE of the
#                                                   step-4 checkpoint —
#                                                   payload staged, manifest
#                                                   not yet published (the
#                                                   torn-write window;
#                                                   latest() must stay on
#                                                   the previous step)
#
# Serving actions (fired by the replica worker at its batch boundary —
# mxnet_tpu/serving/supervisor.py; `batch=` replaces `step=` as the
# when-condition, `replica=` replaces `rank=` as the where-condition):
#
#   MXTPU_FAULT_INJECT="kill_replica@batch=3,replica=0"   hard replica death
#                                                   (SIGKILL/OOM stand-in)
#   MXTPU_FAULT_INJECT="wedge_replica@batch=5,replica=1"  park the replica
#                                                   forever mid-batch (the
#                                                   heartbeat-ejection test
#                                                   vector)
#   MXTPU_FAULT_INJECT="slow_reply@batch=2,ms=500"  delay one reply by ms=
#                                                   (deadline-propagation
#                                                   test vector)
#
# Data-pipeline action (fired by the input pipeline's producer thread —
# mxnet_tpu/data/core.PrefetchBuffer — with the ordinal of the batch it
# just produced; `step=` is that producer-side batch ordinal):
#
#   MXTPU_FAULT_INJECT="slow_batch@step=3,ms=200"   stall PRODUCTION of
#                                                   batch 3 by ms= (the
#                                                   input-jitter chaos
#                                                   vector: a prefetcher
#                                                   with depth*step-time
#                                                   of slack must absorb
#                                                   it without moving
#                                                   step latency)
#
# Server-side surge action (armed per published model by the repository —
# `maybe_inject_load_surge`; `after=` seconds into serving replaces the
# when-condition):
#
#   MXTPU_FAULT_INJECT="load_surge@after=0,rps=200,duration=3"
#                                                   synthetic OPEN-LOOP
#                                                   burst injected at the
#                                                   model's admission queue
#                                                   (the autoscaler chaos
#                                                   vector: drives queue
#                                                   depth + p99 burn, sheds
#                                                   count as 429/503)
#
# Conditions: step (required for training actions) / batch (required for
# serving actions) / after (required for load_surge, seconds), rank /
# replica (default: any), gen (supervision or replica-respawn generation,
# default 0 so a restarted run or respawned replica does NOT re-trigger),
# code (exit status for kill/kill_replica, default 42), ms (slow_reply
# delay, default 1000), rps / duration (load_surge arrival rate and
# length, default 100/s for 2s), dir (corrupt_ckpt target; falls back to
# $MXTPU_CKPT_DIR), grace (preempt only: grace-window seconds overriding
# MXTPU_PREEMPT_GRACE_S). The training hook sits at the trainer step
# boundary — after the optimizer update for `step` completes, before
# anything later runs — which is exactly the crash window that loses
# un-checkpointed progress. kill_during_ckpt instead fires from INSIDE the
# save paths via `_maybe_kill_during_ckpt` (step= matches the checkpoint's
# step), between payload staging and manifest publish.

_FAULT_EXIT_CODE = 42
_TRAIN_ACTIONS = ("kill", "exc", "hang", "corrupt_ckpt", "preempt")
_CKPT_ACTIONS = ("kill_during_ckpt",)
_SERVE_ACTIONS = ("kill_replica", "wedge_replica", "slow_reply")
_SURGE_ACTIONS = ("load_surge",)
_DATA_ACTIONS = ("slow_batch",)
_UNPARSED = object()
_fault_cache = _UNPARSED


def fault_spec(env=None):
    """Parse MXTPU_FAULT_INJECT into a list of {action, step, rank, gen,
    code, dir, batch, replica, ms, grace} dicts. Malformed entries raise
    MXNetError eagerly — a typo'd injection silently never firing would
    invalidate the test using it."""
    raw = (_env.raw("MXTPU_FAULT_INJECT") or "") if env is None else env
    entries = []
    known = (_TRAIN_ACTIONS + _CKPT_ACTIONS + _SERVE_ACTIONS +
             _SURGE_ACTIONS + _DATA_ACTIONS)
    for part in raw.replace(";", " ").split():
        action, _, conds = part.partition("@")
        if action not in known:
            raise MXNetError("MXTPU_FAULT_INJECT: unknown action %r in %r "
                             "(%s)" % (action, part, "|".join(known)))
        entry = {"action": action, "step": None, "rank": None,
                 "gen": 0, "code": _FAULT_EXIT_CODE, "dir": None,
                 "batch": None, "replica": None, "ms": 1000,
                 "after": None, "rps": 100, "duration": 2, "grace": None}
        for cond in filter(None, conds.split(",")):
            k, eq, v = cond.partition("=")
            if not eq or k not in entry or k == "action":
                raise MXNetError("MXTPU_FAULT_INJECT: bad condition %r in %r"
                                 % (cond, part))
            try:
                entry[k] = v if k == "dir" else int(v)
            except ValueError:
                raise MXNetError(
                    "MXTPU_FAULT_INJECT: %s= wants an integer, got %r in %r"
                    % (k, v, part)) from None
        when = "after" if action in _SURGE_ACTIONS \
            else ("batch" if action in _SERVE_ACTIONS else "step")
        if entry[when] is None:
            raise MXNetError("MXTPU_FAULT_INJECT: %r needs a %s= condition"
                             % (part, when))
        entries.append(entry)
    return entries


def _entries():
    """Parse-and-memoize the MXTPU_FAULT_INJECT spec — shared by the
    trainer-step and replica-batch hooks so the no-op path stays one
    cached-empty check."""
    global _fault_cache
    if _fault_cache is _UNPARSED:
        _fault_cache = fault_spec() if _env.is_set("MXTPU_FAULT_INJECT") \
            else []
    return _fault_cache


def _exit_hard(code):
    """Hard death, no cleanup handlers — models SIGKILL/OOM/preemption.
    stdio is flushed so the log prefix trail ends at the right line."""
    import sys

    for s in (sys.stdout, sys.stderr):
        try:
            s.flush()
        except Exception:
            pass
    os._exit(code)


def maybe_inject_fault(step):
    """Trainer-step-boundary hook. No-op (one cached-empty check) unless
    MXTPU_FAULT_INJECT is set. Called by gluon.Trainer.step,
    DistributedTrainer.step and the module.fit batch loop with the number
    of the update that just completed."""
    if not _entries():
        return
    gen = restart_generation()
    rank = _current_rank()
    for e in _entries():
        if e["action"] not in _TRAIN_ACTIONS:
            continue  # fired by the serving hooks, not trainers
        if e["step"] != step or e["gen"] != gen:
            continue
        if e["rank"] is not None and e["rank"] != rank:
            continue
        _fire(e, step, rank)


def maybe_inject_data_stall(batch):
    """Producer-side input-stall hook (`slow_batch@step=,ms=`): called by
    the data pipeline's producer thread (data/core.PrefetchBuffer) with
    the ordinal of the batch it just produced; sleeps ms= on a match. A
    correctly-sized prefetcher absorbs the stall (the consumer keeps
    draining staged batches); an undersized one surfaces it as data_wait
    — which is exactly what the chaos e2e measures. No-op (one
    cached-empty check) unless MXTPU_FAULT_INJECT is set."""
    if not _entries():
        return
    gen = restart_generation()
    rank = _current_rank()
    for e in _entries():
        if e["action"] not in _DATA_ACTIONS:
            continue
        if e["step"] != batch or e["gen"] != gen:
            continue
        if e["rank"] is not None and e["rank"] != rank:
            continue
        _LOG.warning("MXTPU_FAULT_INJECT firing: slow_batch at batch=%d "
                     "rank=%d gen=%d (%dms producer stall)", batch, rank,
                     gen, e["ms"])
        time.sleep(e["ms"] / 1e3)


def maybe_inject_serving_fault(batch, replica):
    """Replica-worker batch-boundary hook (serving/supervisor.py): fires
    the serving actions (`kill_replica` / `wedge_replica` / `slow_reply`)
    when this replica's batch sequence number matches. `gen=` matches the
    replica's respawn generation (MXTPU_RESTART_GENERATION, set by the pool
    supervisor exactly like the elastic launcher sets it), default 0 — so
    a respawned replica does NOT re-trigger and recovery is observable."""
    if not _entries():
        return
    gen = restart_generation()
    for e in _entries():
        if e["action"] not in _SERVE_ACTIONS:
            continue
        if e["batch"] != batch or e["gen"] != gen:
            continue
        if e["replica"] is not None and e["replica"] != replica:
            continue
        _fire_serving(e, batch, replica)


def _fire_serving(entry, batch, replica):
    action = entry["action"]
    _LOG.warning("MXTPU_FAULT_INJECT firing: %s at batch=%d replica=%d "
                 "gen=%d", action, batch, replica, restart_generation())
    if action == "kill_replica":
        _exit_hard(entry["code"])
    if action == "wedge_replica":
        # park mid-batch forever: the router must detect the silence on the
        # heartbeat deadline, eject this replica (process-group teardown)
        # and fail the batch over — SIGKILL is the only way out
        import time as _t

        while True:
            _t.sleep(3600)
    if action == "slow_reply":
        import time as _t

        _t.sleep(entry["ms"] / 1e3)


def maybe_inject_load_surge(model):
    """Server-side chaos hook (`ModelRepository.add`): arm one synthetic
    OPEN-LOOP burst thread per matching ``load_surge@after=,rps=,
    duration=`` entry against the just-published model's admission queue.
    The burst submits fire-and-forget single-example requests at ``rps``
    for ``duration`` seconds — real admissions, so queue depth, the
    `mxtpu_serve_request_seconds` histogram and the SLO burn rates all
    move exactly as they would under a real traffic surge (the
    autoscaler chaos vector, docs/serving.md §Autoscaling). Sheds
    (429/503) are counted, not raised. Predict models only (a model
    without `example_shapes` is skipped). Returns the threads armed."""
    if not _entries():
        return []
    shapes = getattr(model, "example_shapes", None)
    if not shapes:
        return []
    gen = restart_generation()
    threads = []
    for e in _entries():
        if e["action"] not in _SURGE_ACTIONS or e["gen"] != gen:
            continue
        t = threading.Thread(target=_surge_worker, args=(model, dict(e)),
                             name="mxtpu-fault-load-surge", daemon=True)
        t.start()
        threads.append(t)
    return threads


def _surge_worker(model, entry):
    import numpy as _np

    # lazy import: resilience must stay importable without the serving
    # package loaded (model_repository imports THIS module at top level)
    from ..serving.batcher import DrainingError, ModelUnavailableError

    time.sleep(max(0, entry["after"]))
    rps = max(1, entry["rps"])
    duration = max(0, entry["duration"])
    timeout_s = _env.get("MXTPU_SERVE_TIMEOUT_MS") / 1e3
    dtypes = getattr(model, "input_dtypes", None) or {}
    arrays = {k: _np.zeros((1,) + tuple(s), dtype=dtypes.get(k, "float32"))
              for k, s in model.example_shapes.items()}
    telemetry.record_event("fault_load_surge", model=model.name,
                           version=model.version, rps=rps,
                           duration_s=duration)
    _LOG.warning("MXTPU_FAULT_INJECT firing: load_surge on %s/%s "
                 "(%d rps for %ds)", model.name, model.version, rps,
                 duration)
    fired = shed = 0
    period = 1.0 / rps
    end = time.monotonic() + duration
    next_t = time.monotonic()
    while time.monotonic() < end:
        try:
            # open loop: submit and walk away — the resolution (or 504)
            # lands on the request object nobody is waiting on
            model._batcher.submit(arrays,
                                  deadline=time.monotonic() + timeout_s)
            fired += 1
        except (DrainingError, ModelUnavailableError):
            break  # model draining/unloaded under the surge: stop —
            #        hammering a gone model for the remaining duration
            #        would pollute the very shed/availability series the
            #        chaos vector exists to exercise
        except MXNetError:
            shed += 1  # 429/503 shed: the admission layer doing its job
        except Exception:
            break  # model torn down under the surge: stop quietly
        next_t += period
        delay = next_t - time.monotonic()
        if delay > 0:
            time.sleep(delay)
    telemetry.record_event("fault_load_surge_done", model=model.name,
                           version=model.version, fired=fired, shed=shed)


def _fire(entry, step, rank):
    action = entry["action"]
    _LOG.warning("MXTPU_FAULT_INJECT firing: %s at step=%d rank=%d gen=%d",
                 action, step, rank, restart_generation())
    if action == "kill":
        _exit_hard(entry["code"])
    if action == "exc":
        raise MXNetError("injected fault (MXTPU_FAULT_INJECT) at step %d "
                         "rank %d" % (step, rank))
    if action == "hang":
        # park forever at the step boundary — the deterministic stand-in
        # for a wedged collective. Interruptible only by signals: the
        # telemetry watchdog (MXTPU_WATCHDOG_TIMEOUT) should dump + abort,
        # and the launcher's SIGUSR1-then-SIGTERM teardown reaps the rest.
        import time as _t

        while True:
            _t.sleep(3600)
    if action == "corrupt_ckpt":
        directory = entry["dir"] or _env.raw("MXTPU_CKPT_DIR")
        if not directory:
            raise MXNetError("corrupt_ckpt needs dir=... or MXTPU_CKPT_DIR")
        _corrupt_latest(directory)
    if action == "preempt":
        # deterministic stand-in for the cloud preemption notice: deliver
        # a REAL SIGTERM to ourselves so the production handler + grace
        # path runs, not a shortcut around it
        import signal as _signal

        if entry["grace"] is not None:
            _PREEMPT["grace_override"] = float(entry["grace"])
        install_preemption_handler()
        os.kill(os.getpid(), _signal.SIGTERM)


def _maybe_kill_during_ckpt(step):
    """Mid-save chaos hook — called from inside save()/save_sharded()
    AFTER the payload is staged but BEFORE the manifest/meta publish:
    exactly the window where a torn write would be visible if the format
    were not crash-consistent. In async mode this fires on the writer
    thread; os._exit still takes the whole process down, as a real
    mid-write death would."""
    if not _entries():
        return
    gen = restart_generation()
    rank = _current_rank()
    for e in _entries():
        if e["action"] not in _CKPT_ACTIONS:
            continue
        if e["step"] != step or e["gen"] != gen:
            continue
        if e["rank"] is not None and e["rank"] != rank:
            continue
        _LOG.warning("MXTPU_FAULT_INJECT firing: kill_during_ckpt at "
                     "step=%d rank=%d gen=%d (mid-save, pre-publish)",
                     step, rank, gen)
        _exit_hard(e["code"])


def _corrupt_latest(directory):
    """Garble the newest checkpoint's payload IN PLACE (byte flip, same
    length) — the corruption-detection analogue of a bad disk/partial copy.
    Verification must now route latest() to the previous step."""
    mgr = CheckpointManager(directory, rank0_only=False)
    found = mgr.latest()
    if found is None:
        _LOG.warning("corrupt_ckpt: no complete checkpoint under %s",
                     directory)
        return
    _, path = found
    for name in (_PARAMS, _STATES, _META):
        fp = os.path.join(path, name)
        if os.path.exists(fp) and os.path.getsize(fp) > 0:
            with open(fp, "r+b") as f:
                f.seek(os.path.getsize(fp) // 2)
                b = f.read(1)
                f.seek(-1, os.SEEK_CUR)
                f.write(bytes([b[0] ^ 0xFF]))
            _LOG.warning("corrupt_ckpt: flipped a byte in %s", fp)
            return


# --------------------------------------------------------------------------
# Graceful preemption (SIGTERM + grace window)
# --------------------------------------------------------------------------
#
# Contract (docs/fault_tolerance.md §Preemption & elastic resume): the
# preempting agent sends SIGTERM and waits MXTPU_PREEMPT_GRACE_S before the
# SIGKILL. The handler below only records the arrival time — the real work
# happens at the NEXT STEP BOUNDARY via maybe_preempt_exit(): finish the
# in-flight step, emergency-checkpoint inside the remaining grace, exit
# MXTPU_PREEMPT_EXIT_CODE (83). tools/launch.py treats that rc as a
# preemption: the generation restarts WITHOUT consuming --max-restarts
# budget and the restart backoff resets (the generation checkpointed
# cleanly). A failed emergency save exits code+1 (84) instead — that
# generation lost progress, so its restart must consume budget.

# single-slot state written by the signal handler, read at step boundaries.
# mxlint: gil-atomic — a signal handler cannot take locks (it may interrupt
# the very thread holding them); one dict-slot store is atomic under the GIL
_PREEMPT = {"requested_at": None, "grace_override": None, "installed": False,
            "prev_handler": None}


def install_preemption_handler(grace_s=None):
    """Arm the SIGTERM-with-grace contract for this process. Idempotent;
    returns True when the handler is installed. Main thread only —
    signal.signal refuses elsewhere, in which case this returns False and
    SIGTERM keeps its default (immediate-death) behavior."""
    import signal

    if grace_s is not None:
        _PREEMPT["grace_override"] = float(grace_s)
    if _PREEMPT["installed"]:
        return True
    try:
        _PREEMPT["prev_handler"] = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        return False
    _PREEMPT["installed"] = True
    return True


def _on_sigterm(signum, frame):
    # handler body: ONE store, nothing that allocates or locks. The actual
    # work (finish the in-flight step, emergency checkpoint, exit) happens
    # at the next step boundary via maybe_preempt_exit().
    if _PREEMPT["requested_at"] is None:
        _PREEMPT["requested_at"] = time.monotonic()


def preemption_requested():
    """True once SIGTERM arrived (checked by training loops at each step
    boundary; cleared only by process exit — preemption is one-way)."""
    return _PREEMPT["requested_at"] is not None


def preempt_grace_s():
    """The grace window in seconds: a per-run override (installer arg or
    the fault entry's grace=) wins over MXTPU_PREEMPT_GRACE_S."""
    ov = _PREEMPT["grace_override"]
    return float(ov) if ov is not None \
        else float(_env.get("MXTPU_PREEMPT_GRACE_S"))


def preempt_exit_code():
    return int(_env.get("MXTPU_PREEMPT_EXIT_CODE"))


def maybe_preempt_exit(emergency_save=None, rank=None):
    """Step-boundary preemption gate: no-op until SIGTERM arrived; then run
    `emergency_save()` within the grace budget and exit with the preempt
    rc. `emergency_save` must be SYNCHRONOUS and self-contained — flush
    any async writer first (CheckpointManager.flush) so the emergency
    state lands AFTER whatever periodic save was in flight. On save
    failure the exit code is preempt_exit_code()+1: no checkpoint landed,
    so the launcher must treat the restart as budget-consuming."""
    if _PREEMPT["requested_at"] is None:
        return
    grace = preempt_grace_s()
    deadline = _PREEMPT["requested_at"] + grace
    rank = _current_rank() if rank is None else rank
    code = preempt_exit_code()
    _LOG.warning("preemption: SIGTERM received; emergency checkpoint within "
                 "%.1fs grace, then exit rc=%d (rank %d)", grace, code, rank)
    telemetry.record_event("preempt_begin", rank=rank, grace_s=grace,
                           generation=restart_generation())
    try:
        if emergency_save is not None:
            emergency_save()
        margin = deadline - time.monotonic()
        if margin < 0:
            _LOG.warning("preemption: emergency checkpoint overran the "
                         "grace window by %.1fs — raise "
                         "MXTPU_PREEMPT_GRACE_S or shrink the payload",
                         -margin)
        telemetry.record_event("preempt_checkpoint", rank=rank,
                               margin_s=round(margin, 3),
                               generation=restart_generation())
    except Exception:
        _LOG.exception("preemption: emergency checkpoint FAILED; exiting "
                       "rc=%d (budget-consuming)", code + 1)
        telemetry.record_event("preempt_checkpoint_failed", rank=rank,
                               generation=restart_generation())
        code = code + 1
    try:
        # os._exit skips atexit: flush the telemetry JSONL explicitly so
        # the preempt events above survive into the flight record
        telemetry.flush(reason="preempt")
    except Exception:
        pass
    _exit_hard(code)
