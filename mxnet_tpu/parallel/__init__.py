"""mxnet_tpu.parallel — scaling subsystem (SURVEY §2.3, §5.8).

Replaces the reference's kvstore transports + executor-group batch slicing
with mesh-sharded compiled steps:

  mesh        — named-axis device meshes (dp/fsdp/tp/pp/sp/ep)
  sharding    — parameter/data PartitionSpec rules
  collectives — XLA collectives (psum/all_gather/reduce_scatter/ppermute)
                + multi-host bootstrap (jax.distributed rendezvous)
  trainer     — DistributedTrainer: fwd+loss+bwd+optimizer as ONE compiled
                sharded step with donated buffers
  sharded_trainer — ShardedTrainer: the same fused step with a
                cross-process-stable key + device-topology fingerprint, so
                its executables persist (MXTPU_COMPILE_CACHE) and restarts
                reach step 1 with zero compiles; also ModuleFusedStep, the
                module.fit() promotion
  ring_attention — exact sequence-parallel attention over the sp axis
  pipeline    — GPipe-style microbatch pipeline over the pp axis
  pipeline_trainer — PipelineTrainer: pipeline a real Gluon model
                (BERT encoder stack) end-to-end incl. optimizer
  resilience  — fault tolerance: crash-consistent CheckpointManager,
                auto-resume, MXTPU_FAULT_INJECT harness (pairs with the
                elastic tools/launch.py --max-restarts supervisor)
  (expert parallelism: gluon.contrib.moe.MoEFFN + the `ep` sharding rule)
"""
from .mesh import (make_mesh, default_mesh, current_mesh, use_mesh,
                   local_devices, DP, FSDP, TP, PP, SP, EP)
from .sharding import (ShardingRules, named_sharding, shard_array, batch_spec,
                       param_spec, constraint)
from . import collectives
from .collectives import (init_process_group, rank, num_workers, barrier,
                          all_reduce_arrays)
from .trainer import DistributedTrainer
from .sharded_trainer import ShardedTrainer, ModuleFusedStep
from . import resilience
from .resilience import CheckpointManager, maybe_inject_fault
from .ring_attention import ring_attention, ring_attention_sharded
from .pipeline import pipeline_apply, pipeline_stack_params
from .pipeline_trainer import PipelineTrainer

__all__ = [
    "make_mesh", "default_mesh", "current_mesh", "use_mesh", "local_devices",
    "DP", "FSDP", "TP", "PP", "SP", "EP",
    "ShardingRules", "named_sharding", "shard_array", "batch_spec",
    "param_spec", "constraint", "collectives", "init_process_group", "rank",
    "num_workers", "barrier", "all_reduce_arrays", "DistributedTrainer",
    "ShardedTrainer", "ModuleFusedStep",
    "resilience", "CheckpointManager", "maybe_inject_fault",
    "ring_attention", "ring_attention_sharded",
    "pipeline_apply", "pipeline_stack_params", "PipelineTrainer",
]
