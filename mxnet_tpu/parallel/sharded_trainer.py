"""ShardedTrainer — the whole-step compiled training path, promoted to the
user-facing API and to the PERSISTENT artifact tier.

`DistributedTrainer` already fuses forward + loss + backward + optimizer
update into one donated sharded executable, but keys it by a process-local
instance token (`no_persist=True`): every restart recompiles from scratch
(ROADMAP item 1 — the quarantine this module lifts). ShardedTrainer keeps
the exact step machinery and changes only the executable's IDENTITY:

  * a **stable cross-process fingerprint** — block architecture + source,
    sorted (param, shape, dtype, grad_req), resolved PartitionSpecs,
    optimizer class + hyperparameters, loss identity, amp dtype — replaces
    the instance token, so two processes training the same configuration
    name the same executable;
  * the key carries the mesh's **device-topology fingerprint**
    (`mesh.mesh_fingerprint`: axis names x shape x device kinds x process
    count), which is what lets a sharded+donated key reach the persistent
    tier honestly (compile/registry._dir): the serialized step deserializes
    only onto the same geometry — a different mesh is a clean digest miss;
  * every fill/load is recorded into a **warmup manifest** keyed by
    (fingerprint, topology), and a fresh trainer prefetches that manifest
    before its first step — a restarted generation
    (tools/launch.py --compile-cache --max-restarts) reaches step 1 with
    ZERO ``jit_compile`` events.

Reachable from the user API as ``gluon.Trainer(..., sharded=True,
block=net, loss=loss)`` (or armed fleet-wide via ``MXTPU_SHARDED_STEP``)
and from ``module.fit`` without model-code changes (Module.fused_step
resolves through the same persistence bracket). docs/sharded_training.md
is the operator-facing writeup.
"""
from __future__ import annotations

import hashlib
import json

from ..base import MXNetError
from .mesh import current_mesh, mesh_fingerprint
from .sharding import batch_spec, named_sharding
from .trainer import DistributedTrainer, _host_lr, _traced_update, _tree_map

__all__ = ["ShardedTrainer", "ModuleFusedStep", "stable_fingerprint",
           "optimizer_fingerprint"]


# ---------------------------------------------------------------------------
# stable cross-process fingerprints
# ---------------------------------------------------------------------------

def _source_digest(obj):
    """sha256 of an object's class source (falls back to the qualname when
    source is unavailable — builtins, exec'd code): the forward's python is
    part of the traced program, so it belongs in the executable identity."""
    import inspect

    cls = obj if inspect.isclass(obj) or inspect.isfunction(obj) \
        else type(obj)
    try:
        src = inspect.getsource(cls)
    except (OSError, TypeError):
        src = getattr(cls, "__qualname__", repr(cls))
    return hashlib.sha256(src.encode()).hexdigest()[:16]


def optimizer_fingerprint(optimizer):
    """Deterministic rendering of an optimizer's identity: class + every
    primitive hyperparameter (lr/wd/momentum/...), EXCLUDING the volatile
    update counters — a restarted run mid-schedule must still hit (the
    update count and scheduled lr are runtime inputs of the fused step)."""
    hp = {k: v for k, v in sorted(vars(optimizer).items())
          if isinstance(v, (int, float, bool, str))
          and k not in ("num_update", "begin_num_update")}
    return "%s:%s" % (type(optimizer).__qualname__,
                      json.dumps(hp, sort_keys=True))


def stable_fingerprint(block, params, specs, optimizer, loss=None,
                       amp_dtype=None, loss_inputs=None):
    """The cross-process half of a ShardedTrainer executable key: identical
    training configurations in different processes (a restarted elastic
    generation) resolve to the same fingerprint; any change to the
    architecture, parameter set, layout, optimizer or loss changes it.
    ``params`` is the sorted (name, NDArray) list, ``specs`` the resolved
    per-parameter PartitionSpecs (layout is identity: a re-ruled trainer
    compiles a different program)."""
    loss_id = None
    if loss is not None:
        loss_id = "%s:%s" % (getattr(loss, "__qualname__",
                                     type(loss).__qualname__),
                             _source_digest(loss))
    blob = json.dumps({
        "block": type(block).__qualname__,
        "block_repr": repr(block),
        "block_src": _source_digest(block),
        "params": [(n, list(nd_.shape), str(nd_.dtype))
                   for n, nd_ in params],
        "specs": [str(s) for s in specs],
        "optimizer": optimizer_fingerprint(optimizer),
        "loss": loss_id,
        "amp": str(amp_dtype) if amp_dtype is not None else None,
        "loss_inputs": loss_inputs,
    }, sort_keys=True, separators=(",", ":"))
    return "sharded:" + hashlib.sha256(blob.encode()).hexdigest()[:40]


# ---------------------------------------------------------------------------
# the persistence bracket shared by ShardedTrainer and ModuleFusedStep
# ---------------------------------------------------------------------------

class _PersistentStepMixin:
    """Wraps registry resolution with the restart contract: prefetch the
    training manifest once (before the first fill can compile), and record
    every persistable fill/load back into it — so the NEXT process starts
    zero-compile."""

    def _init_persist(self, manifest_seed):
        self._manifest_seed = manifest_seed
        self._manifest_id = hashlib.sha256(
            manifest_seed.encode()).hexdigest()[:24]
        self._manifest_entries = []
        self._prefetched = False

    @property
    def manifest_id(self):
        """The warmup-manifest id this trainer records under (stable for
        one (fingerprint, topology) pair across processes)."""
        return self._manifest_id

    def _resolve_persistent(self, key, build, **kw):
        from .. import compile as _compile

        value = _compile.lookup(key)
        if value is not None:
            # steady state: the memory tier answers, no bracket needed
            return value
        directory = _compile.cache_dir()
        if directory is None:
            return _compile.get_or_build(key, build, **kw)
        from .. import env as _env

        if not self._prefetched:
            self._prefetched = True
            if _env.get("MXTPU_SHARDED_PREFETCH"):
                n = _compile.prefetch(self._manifest_id, directory=directory)
                if n:
                    from ..telemetry import recorder as _rec

                    _rec.record_event("sharded_manifest_prefetch",
                                      manifest=self._manifest_id, staged=n)
        reg = _compile.registry()
        cursor = reg.mark()
        fn = _compile.get_or_build(key, build, **kw)
        fresh = reg.keys_since(cursor)
        if fresh:
            self._manifest_entries.extend(fresh)
            _compile.write_manifest(directory, self._manifest_id,
                                    self._manifest_entries,
                                    model=self._manifest_seed[:64])
        return fn


# ---------------------------------------------------------------------------
# the promoted trainer
# ---------------------------------------------------------------------------

class ShardedTrainer(_PersistentStepMixin, DistributedTrainer):
    """`DistributedTrainer` with persistent, cross-process executable
    identity (module docstring). Same constructor and step()/forward()/
    sync_params()/checkpoint surface; the only behavioral delta is where
    the fused step's executable comes from on a warm restart: the
    persistent artifact tier instead of a recompile."""

    def __init__(self, block, optimizer, optimizer_params=None, loss=None,
                 mesh=None, rules=None, amp_dtype=None, loss_inputs=None):
        super().__init__(block, optimizer, optimizer_params=optimizer_params,
                         loss=loss, mesh=mesh, rules=rules,
                         amp_dtype=amp_dtype, loss_inputs=loss_inputs)
        self._topology = mesh_fingerprint(self._mesh)
        # replace the process-local instance token with the stable
        # cross-process fingerprint (the quarantine lift)
        param_items = list(zip(self._param_names, self._param_nds))
        specs = [sh.spec for sh in self._shardings]
        self._compile_token = stable_fingerprint(
            block, param_items, specs, self._optimizer, loss=loss,
            amp_dtype=amp_dtype, loss_inputs=loss_inputs)
        self._init_persist("%s|%s" % (self._compile_token, self._topology))

    @property
    def topology(self):
        """This trainer's device-topology fingerprint (the
        `ExecutableKey.topology` component)."""
        return self._topology

    def _step_key(self, sig):
        from .. import compile as _compile

        return _compile.ExecutableKey("sharded_step", self._compile_token,
                                      shapes=sig, sharded=True,
                                      donation=(3, 4),
                                      topology=self._topology)

    def _forward_key(self, sig):
        from .. import compile as _compile

        return _compile.ExecutableKey("sharded_forward", self._compile_token,
                                      shapes=sig, sharded=True,
                                      topology=self._topology)

    def _resolve(self, key, build, **kw):
        return self._resolve_persistent(key, build, **kw)


# ---------------------------------------------------------------------------
# module.fit promotion: the symbolic whole-step executable
# ---------------------------------------------------------------------------

class ModuleFusedStep(_PersistentStepMixin):
    """One compiled executable for a Module's training step: graph forward
    (`symbol._interpret`) + backward (`jax.vjp`, ones cotangents — the
    loss-head convention executor.backward documents) + the traced
    optimizer update, with donated parameter/state buffers. Built lazily
    by `Module.fused_step` when ``MXTPU_SHARDED_STEP`` is armed; the
    executable key rides the graph-json fingerprint (stable across
    processes) + the optimizer fingerprint + the mesh topology, so fused
    fit steps persist and restart zero-compile exactly like
    ShardedTrainer's."""

    def __init__(self, executor, optimizer, param_names):
        self._exec = executor
        self._optimizer = optimizer
        arg_names = executor._arg_names
        params = set(param_names)
        self._wrt = [i for i, n in enumerate(arg_names)
                     if n in params
                     and executor.grad_req.get(n, "null") != "null"]
        if not self._wrt:
            raise MXNetError("no trainable parameters to fuse")
        # updater indices: position within the Module's param_names (the
        # op-by-op update() convention, so optimizer state save/load and
        # param_idx2name agree between the two paths)
        self._upd_idx = [param_names.index(arg_names[i]) for i in self._wrt]
        self._fixed = [i for i, n in enumerate(arg_names)
                       if n in params and i not in self._wrt]
        self._feeds = [i for i, n in enumerate(arg_names) if n not in params]
        self._states = None
        self._step_count = 0
        mesh = executor._mesh
        self._topology = mesh_fingerprint(mesh) if mesh is not None else None
        fingerprint, self._no_persist = executor._graph_meta()
        self._opt_fp = optimizer_fingerprint(optimizer)
        self._fingerprint = "module:" + hashlib.sha256(
            ("%s|%s" % (fingerprint, self._opt_fp)).encode()).hexdigest()[:40]
        self._init_persist("%s|%s" % (self._fingerprint,
                                      self._topology or "local"))

    @property
    def step_count(self):
        return self._step_count

    # -- state --------------------------------------------------------------
    def _ensure_states(self):
        if self._states is not None:
            return
        ex = self._exec
        self._states = []
        for k, i in enumerate(self._wrt):
            st = self._optimizer.create_state_multi_precision(
                self._upd_idx[k], ex.arg_arrays[i])
            self._states.append(_tree_map(lambda s: s._data, st))

    def sync_updater(self, updater):
        """Write the fused path's device-side optimizer states back into an
        op-by-op Updater (Module.save_optimizer_states interop)."""
        import numpy as np

        import jax

        from ..ndarray import NDArray

        if self._states is None:
            return
        ctx = self._exec._ctx
        for k, idx in enumerate(self._upd_idx):
            updater.states[idx] = _tree_map(
                lambda a: NDArray(np.asarray(jax.device_get(a)), ctx=ctx),
                self._states[k])
            updater.states_synced[idx] = True

    # -- the executable -----------------------------------------------------
    def _build(self, n_feeds):
        import jax
        import jax.numpy as jnp

        ex = self._exec
        symbol = ex._symbol
        arg_names, aux_names = ex._arg_names, ex._aux_names
        wrt, fixed, feeds = self._wrt, self._fixed, self._feeds
        optimizer, upd_idx, ctx = self._optimizer, self._upd_idx, ex._ctx

        def step(key, t, lr, train_arrays, states, fixed_arrays, aux_arrays,
                 *feed_arrays):
            def fwd(train_arrs):
                full = [None] * len(arg_names)
                for k, i in enumerate(fixed):
                    full[i] = fixed_arrays[k]
                for k, i in enumerate(feeds):
                    full[i] = feed_arrays[k]
                for k, i in enumerate(wrt):
                    full[i] = train_arrs[k]
                values = dict(zip(arg_names, full))
                values.update(zip(aux_names, aux_arrays))
                outs, aux_up = symbol._interpret(values, is_train=True,
                                                 rng_key=key)
                new_aux = tuple(aux_up.get(n, values[n]) for n in aux_names)
                return tuple(outs), new_aux

            outs, pull, new_aux = jax.vjp(fwd, tuple(
                train_arrays[k] for k in range(len(wrt))), has_aux=True)
            # ones cotangents: loss-head ops carry cotangent-independent
            # custom_vjps (the reference's head-gradient convention)
            cots = tuple(jnp.ones(tuple(o.shape), o.dtype) for o in outs)
            grads = list(pull(cots)[0])
            new_w, new_s = _traced_update(optimizer, ctx, upd_idx,
                                          list(train_arrays), grads, states,
                                          t, lr)
            return outs, new_w, new_s, new_aux

        mesh = ex._mesh
        if mesh is None:
            return jax.jit(step, donate_argnums=(3, 4))
        from jax.sharding import PartitionSpec

        repl = named_sharding(mesh, PartitionSpec())
        feed_sh = [named_sharding(
            mesh, batch_spec(mesh, ex.arg_arrays[i].ndim))
            for i in feeds]
        return jax.jit(
            step,
            in_shardings=(repl, repl, repl, [repl] * len(wrt),
                          _tree_map(lambda s: repl, self._states),
                          [repl] * len(fixed),
                          tuple(repl for _ in aux_names), *feed_sh),
            donate_argnums=(3, 4))

    def _key(self, sig):
        from .. import compile as _compile

        return _compile.ExecutableKey(
            "module_fused_step", self._fingerprint, shapes=sig,
            static=(tuple(self._wrt), self._exec._mesh_desc()),
            sharded=self._exec._mesh is not None, donation=(3, 4),
            no_persist=self._no_persist, topology=self._topology)

    # -- one step -----------------------------------------------------------
    def __call__(self, feed_dict):
        """Run one fused train step. ``feed_dict`` maps data/label arg
        names to NDArrays; outputs land in ``executor.outputs`` (device-
        side — the metric asks for the host copy, the step never does)."""
        import jax.numpy as jnp

        from .. import random as _random, telemetry
        from ..ndarray import NDArray

        ex = self._exec
        self._ensure_states()
        for i in self._feeds:
            name = ex._arg_names[i]
            if name not in feed_dict:
                raise MXNetError("fused step missing input '%s'" % name)
            val = feed_dict[name]
            ex.arg_arrays[i] = val if isinstance(val, NDArray) \
                else NDArray(jnp.asarray(val), ctx=ex._ctx)
        ex._place_inputs()

        train = [ex.arg_arrays[i]._data for i in self._wrt]
        fixed = [ex.arg_arrays[i]._data for i in self._fixed]
        aux = tuple(a._data for a in ex.aux_arrays)
        feed = [ex.arg_arrays[i]._data for i in self._feeds]
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in train + feed)

        # minted BEFORE the fill: the AOT lower must never initialize the
        # RNG chain inside its trace (parallel/trainer.py step())
        key = _random.next_key()

        def example_avals():
            import jax

            aval = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
            return (aval(key), jax.ShapeDtypeStruct((), "float32"),
                    jax.ShapeDtypeStruct((), "float32"),
                    [aval(a) for a in train],
                    _tree_map(aval, list(self._states)),
                    [aval(a) for a in fixed],
                    tuple(aval(a) for a in aux),
                    *[aval(a) for a in feed])

        fn = self._resolve_persistent(
            self._key(sig),
            lambda: self._build(len(feed)),
            label="module_fused_step",
            example_args=example_avals,
            on_fill=lambda: telemetry.counter(
                "mxtpu_executor_build_total",
                {"what": "module_fused_step"}).inc(),
            event_fields={"batch_sig": str(sig)})

        self._step_count += 1
        o = self._optimizer
        o.num_update = max(self._step_count + o.begin_num_update,
                           o.num_update)
        lr = _host_lr(o)
        t = jnp.asarray(self._step_count, dtype=jnp.float32)
        outs, new_w, new_s, new_aux = fn(
            key, t, jnp.asarray(lr, dtype=jnp.float32), train,
            self._states, fixed, aux, *feed)
        self._states = new_s
        # donated buffers are dead: swap the fresh arrays straight into the
        # executor's NDArray views (no host copy anywhere on this path)
        for k, i in enumerate(self._wrt):
            ex.arg_arrays[i]._set_data(new_w[k])
        for dst, src in zip(ex.aux_arrays, new_aux):
            dst._set_data(src)
        ex.outputs = [NDArray(out, ctx=ex._ctx) for out in outs]
        return ex.outputs
