"""Parameter/data sharding rules.

The reference distributes by *copying*: one parameter NDArray per device
context (gluon/parameter.py `_init_impl` per-ctx copies) plus kvstore
reduce/broadcast. The TPU-native model keeps ONE logical array per
parameter, laid out over the mesh by a `PartitionSpec`; XLA inserts the
collectives (SURVEY §2.3). This module decides the PartitionSpec for each
parameter from name/shape rules.

Rule resolution order:
  1. explicit per-parameter spec (``rules[name]`` exact or regex match)
  2. tensor-parallel heuristics when the mesh has a ``tp`` axis
     (Dense/Conv weight matrices sharded on the output or input dim,
     alternating column-/row-parallel is the caller's job via rules)
  3. fsdp: shard the largest divisible dim over the ``fsdp`` axis
  4. replicate
"""
from __future__ import annotations

import re

from .mesh import DP, EP, FSDP, TP

__all__ = ["ShardingRules", "named_sharding", "shard_array", "batch_spec",
           "param_spec", "constraint"]


def _P(*parts):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*parts)


def named_sharding(mesh, spec):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, spec)


class ShardingRules:
    """Maps parameter name → PartitionSpec over a given mesh.

    ``rules`` — ordered {pattern: spec-template} where pattern is a regex
    fullmatched against the parameter name and spec-template is a tuple of
    axis names / None / tuples, or the string "auto".
    """

    def __init__(self, rules=None, fsdp_min_size=2 ** 10):
        self.rules = dict(rules or {})
        self.fsdp_min_size = fsdp_min_size

    def spec_for(self, name, shape, mesh):
        axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        for pat, spec in self.rules.items():
            if pat == name or re.fullmatch(pat, name):
                if spec == "auto":
                    break
                return _P(*spec)
        # -- heuristics --
        shape = tuple(shape or ())
        if not shape:
            return _P()
        parts = [None] * len(shape)
        if EP in axis_sizes and axis_sizes[EP] > 1 and "expert" in name \
                and shape and shape[0] % axis_sizes[EP] == 0:
            # MoE expert tables (E, ...) live expert-parallel: the dispatch
            # einsum reshards tokens over `ep` (XLA inserts the all_to_all)
            parts[0] = EP
        if TP in axis_sizes and axis_sizes[TP] > 1 and parts[0] is None:
            # column-parallel by default: shard dim 0 (out-features for Dense
            # [out,in]; out-channels for Conv OIHW-style weights) — unless a
            # higher-priority rule (EP expert tables) already claimed dim 0
            if shape[0] % axis_sizes[TP] == 0 and shape[0] >= axis_sizes[TP]:
                parts[0] = TP
        if FSDP in axis_sizes and axis_sizes[FSDP] > 1:
            size = 1
            for s in shape:
                size *= s
            if size >= self.fsdp_min_size:
                # shard the largest not-yet-sharded divisible dim
                order = sorted(range(len(shape)), key=lambda i: -shape[i])
                for i in order:
                    if parts[i] is None and shape[i] % axis_sizes[FSDP] == 0:
                        parts[i] = FSDP
                        break
        while parts and parts[-1] is None:
            parts.pop()
        return _P(*parts)

    def sharding_for(self, name, shape, mesh):
        return named_sharding(mesh, self.spec_for(name, shape, mesh))


def param_spec(name, shape, mesh, rules=None):
    return (rules or ShardingRules()).spec_for(name, shape, mesh)


def batch_spec(mesh, ndim=None, axes=(DP, FSDP)):
    """PartitionSpec for a batch-leading data array: batch dim sharded over
    the data axes present in the mesh (dp and fsdp both carry batch)."""
    present = [a for a in axes if a in mesh.axis_names
               and dict(zip(mesh.axis_names, mesh.devices.shape))[a] > 1]
    first = tuple(present) if len(present) > 1 else (present[0] if present else None)
    if ndim is None:
        return _P(first)
    return _P(*([first] + [None] * (ndim - 1)))


def shard_array(x, mesh, spec):
    import jax

    return jax.device_put(x, named_sharding(mesh, spec))


def constraint(x, spec, mesh=None):
    """with_sharding_constraint usable inside jit — the in-graph annotation
    that replaces the reference's group2ctx device placement attrs
    (graph_executor.cc PlaceDevice)."""
    import jax

    from .mesh import current_mesh

    mesh = mesh or current_mesh()
    return jax.lax.with_sharding_constraint(x, named_sharding(mesh, spec))
