"""Ring attention — sequence/context parallelism over the `sp` mesh axis.

Not present in the reference (SURVEY §5.7: long sequences were handled by
bucketing only); this is the TPU-native long-context extension the build
plan calls for. Q/K/V are sharded on the sequence dimension across `sp`;
each device keeps its Q shard resident and the K/V shards rotate around
the ring via `ppermute` (one ICI hop per step), overlapping the transfer
with the local block's attention math. Softmax is accumulated online
(running max / running sum), so the result is exact — identical to full
attention — while no device ever materializes the full [L, L] score
matrix or the full K/V.
"""
from __future__ import annotations

import functools

__all__ = ["ring_attention", "ring_attention_sharded", "local_attention_block"]


def local_attention_block(q, k, v, o, m, l, causal, q_off, kv_off, scale):
    """One blockwise-attention accumulation step (online softmax).

    q: [B, Lq, H, D]; k, v: [B, Lk, H, D]; o: [B, Lq, H, D] accumulator;
    m, l: [B, H, Lq] running max / normalizer. Returns updated (o, m, l).
    """
    import jax.numpy as jnp

    # scores [B, H, Lq, Lk] — contraction on D via MXU
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        qpos = q_off + jnp.arange(lq)[:, None]
        kpos = kv_off + jnp.arange(lk)[None, :]
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard fully-masked rows (all -inf): keep them at zero contribution
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
    l_new = alpha * l + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def ring_attention(q, k, v, axis_name="sp", causal=False, scale=None):
    """Exact attention with K/V rotating around the `axis_name` ring.

    Call inside shard_map/pjit where q/k/v are the *local* sequence shards
    [B, L_local, H, D]. Returns the local output shard [B, L_local, H, D].
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    o0 = jnp.zeros((b, lq, h, d), jnp.float32)
    m0 = jnp.full((b, h, lq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    q_off = idx * lq

    def body(step, carry):
        o, m, l, kc, vc = carry
        src = (idx - step) % n           # whose K/V shard we now hold
        kv_off = src * lk
        o, m, l = local_attention_block(q, kc, vc, o, m, l, causal,
                                        q_off, kv_off, scale)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return o, m, l, kc, vc

    o, m, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    l = jnp.where(l == 0.0, 1.0, l)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh=None, axis_name="sp", causal=False,
                           scale=None, batch_axis="dp"):
    """Host-callable wrapper: shards [B, L, H, D] inputs over the mesh
    (batch on `dp`, sequence on `sp`) and runs ring_attention under
    shard_map. Jit-compatible."""
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    from .mesh import current_mesh

    mesh = mesh or current_mesh()
    bat = batch_axis if batch_axis in mesh.axis_names else None
    seq = axis_name if axis_name in mesh.axis_names else None
    spec = P(bat, seq, None, None)

    body = functools.partial(ring_attention, axis_name=axis_name,
                             causal=causal, scale=scale)
    try:
        fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    except TypeError:  # pre-0.9 jax uses check_rep
        fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_rep=False)
    if seq is None:
        raise ValueError(f"mesh {mesh.axis_names} has no '{axis_name}' axis")
    return fn(q, k, v)
