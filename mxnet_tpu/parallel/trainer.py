"""DistributedTrainer — the scaled training path.

The reference scales by copying parameters per device and reducing grads
through a kvstore (gluon/trainer.py:27 + kvstore_dist.h / kvstore_nccl.h).
The TPU-native model compiles ONE training step over the whole mesh:

  * each parameter is a single logical jax.Array laid out by a
    PartitionSpec (sharding.ShardingRules);
  * the batch is sharded over the data axes;
  * forward + loss + backward + optimizer update are ONE jit-compiled
    function with donated param/state buffers — XLA inserts the grad
    all-reduces (psum over dp), the fsdp all-gathers/reduce-scatters and
    the tp collectives, and they ride ICI;
  * any registered mxnet_tpu.optimizer.Optimizer works: its `update()` is
    traced into the step (the fused optimizer ops are pure functions, see
    ops/optimizer_ops.py), with the update count `t` and scheduled `lr`
    fed in as device scalars so one executable serves every step.

This subsumes the reference's dist_sync kvstore semantics (synchronous
data parallelism); dist_async is intentionally not reproduced (SURVEY
§2.3 divergence note).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .. import optimizer as opt_mod
from .mesh import current_mesh
from .sharding import ShardingRules, batch_spec, named_sharding

__all__ = ["DistributedTrainer"]


def _tree_map(fn, *trees):
    """tree_map over optimizer-state pytrees. NDArray is not a registered
    pytree node, so mark it (and any non-container) as a leaf explicitly."""
    import jax

    return jax.tree_util.tree_map(
        fn, *trees,
        is_leaf=lambda x: x is not None and not isinstance(x, (list, tuple, dict)))


def _host_lr(optimizer):
    """Current learning rate resolved on the host (scheduler included)."""
    o = optimizer
    return float(o.lr_scheduler(max(o.num_update, 1))) if o.lr_scheduler \
        else o.lr


def _traced_update(optimizer, ctx, keys, weights, grads, states, t, lr):
    """Trace optimizer.update() for each weight key with the update count
    and learning rate fed as device scalars, so ONE executable serves every
    step (no per-step recompile from e.g. Adam's bias correction). The
    optimizer's host-side counters/scheduler are stubbed out for the trace
    and restored after. Shared by DistributedTrainer and PipelineTrainer."""
    from ..ndarray import NDArray

    o = optimizer
    saved = (o._index_update_count.copy(), o.num_update, o.lr,
             o.lr_scheduler, o._update_count)
    try:
        o._index_update_count = {i: t for i in keys}
        o._update_count = lambda index: None
        o.lr_scheduler = None
        o.lr = lr
        new_w, new_s = [], []
        for k, i in enumerate(keys):
            w = NDArray(weights[k], ctx=ctx)
            g = NDArray(grads[k], ctx=ctx)
            s = _tree_map(lambda a: NDArray(a, ctx=ctx), states[k])
            o.update_multi_precision(i, w, g, s)
            new_w.append(w._data)
            new_s.append(_tree_map(lambda nd_: nd_._data, s))
        return new_w, new_s
    finally:
        (o._index_update_count, o.num_update, o.lr, o.lr_scheduler,
         o._update_count) = saved


class DistributedTrainer:
    """Compiled sharded training over a mesh.

    Parameters
    ----------
    block : gluon.Block — initialized (single context); its parameters are
        moved onto the mesh and updated functionally. Call `sync_params()`
        to copy trained values back into the block for save/export.
    optimizer : str or Optimizer
    loss : gluon loss Block / callable(pred, label) -> per-sample loss.
    mesh : jax.sharding.Mesh (default: parallel.current_mesh())
    rules : ShardingRules for parameter layout (default heuristics).
    loss_inputs : what a multi-output model feeds the loss —
        "pred" (first output only), "outputs" (the full output tuple, for
        auxiliary terms like MoE load-balance/z-loss), or None (default):
        gluon loss Blocks get "pred", plain callables get "outputs" when
        the model returns several values. Single-output models always
        behave as "pred".
    """

    def __init__(self, block, optimizer, optimizer_params=None, loss=None,
                 mesh=None, rules=None, amp_dtype=None, loss_inputs=None):
        import jax

        self._block = block
        self._mesh = mesh or current_mesh()
        self._rules = rules or ShardingRules()
        self._loss = loss
        if loss_inputs not in (None, "pred", "outputs"):
            raise MXNetError("loss_inputs must be None, 'pred' or 'outputs'")
        self._loss_inputs = loss_inputs
        # mixed precision: compute forward/backward in `amp_dtype`
        # (bfloat16 — the MXU's native dtype) while parameters, gradients
        # as accumulated through the cast's vjp, and the optimizer update
        # stay fp32 (master weights; reference analogue: multi_precision)
        self._amp_dtype = amp_dtype

        param_items = sorted(block.collect_params().items())
        if not param_items:
            raise MXNetError("block has no parameters; initialize() it first")
        self._param_names = [n for n, _ in param_items]
        self._params = [p for _, p in param_items]
        # NDArray views (one per param, on the block's context) — these are
        # the objects whose buffers get swapped during tracing
        ctx = self._params[0].list_ctx()[0]
        self._param_nds = [p.data(ctx) for p in self._params]
        self._trainable = [i for i, p in enumerate(self._params)
                           if p.grad_req != "null"]
        self._aux = [i for i, p in enumerate(self._params) if p.grad_req == "null"]

        optimizer_params = optimizer_params or {}
        if isinstance(optimizer, opt_mod.Optimizer):
            self._optimizer = optimizer
        else:
            self._optimizer = opt_mod.create(optimizer, **optimizer_params)
        self._optimizer.param_dict = {i: self._params[i] for i in self._trainable}

        # -- move parameters onto the mesh ---------------------------------
        self._shardings = []
        self._arrays = []
        for name, p, nd_ in zip(self._param_names, self._params, self._param_nds):
            sh = self._rules.sharding_for(name, nd_.shape, self._mesh)
            self._shardings.append(sh)
            # fresh device-side copy: device_put may alias a matching
            # shard with the block's live buffer, and step()'s donation
            # would then delete the param out from under the block
            import jax.numpy as jnp

            self._arrays.append(jax.device_put(
                jnp.array(nd_._data, copy=True), sh))

        # -- optimizer state pytree (sharded like its weight) --------------
        self._states = []
        self._state_shardings = []
        for i in self._trainable:
            st = self._optimizer.create_state_multi_precision(
                i, self._param_nds[i])
            sh = self._shardings[i]
            self._states.append(_tree_map(
                lambda s: jax.device_put(s._data, sh), st))
            self._state_shardings.append(_tree_map(lambda s: sh, st))

        self._step_count = 0
        # executables resolve through mxnet_tpu.compile (keyed by this
        # process-local token x batch signature); the local dict only
        # carries forward's trace-time aux ordering metadata
        from .. import compile as _compile

        self._compile_token = _compile.instance_token("DistributedTrainer")
        self._fwd_compiled = {}

    # ------------------------------------------------------------------
    @property
    def optimizer(self):
        return self._optimizer

    @property
    def mesh(self):
        return self._mesh

    @property
    def learning_rate(self):
        return self._host_lr()

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    def _host_lr(self):
        return _host_lr(self._optimizer)

    # ------------------------------------------------------------------
    def _trace_forward(self, batch_arrays, param_arrays, key, is_train):
        """Run the block's eager forward with traced buffers swapped in.
        Same mechanism as HybridBlock._build_cache (gluon/block.py)."""
        from .. import autograd, random as _random
        from ..ndarray import NDArray
        from ..gluon import block as block_mod

        ctx = self._params[0].list_ctx()[0]
        # mxlint: trace-pure — routes the traced step key through the
        # RNG chain for the trace's duration; restored in finally
        prev_key = _random.push_trace_key(key)
        saved = [(nd_, nd_._data, nd_._version) for nd_ in self._param_nds]
        block_mod._TRACING.flag = True
        try:
            for nd_, arr in zip(self._param_nds, param_arrays):
                nd_._data = arr
            call_args = [NDArray(a, ctx=ctx) for a in batch_arrays]
            # enter the params' ctx: ops that create fresh arrays mid-forward
            # (arange position ids, masks) must land on the same ctx or
            # sub-blocks fed by them request params on the ambient default
            with ctx:
                with autograd._scope(recording=False, training=is_train):
                    out = self._block(*call_args)
            aux_updates = {}
            for i in self._aux:
                if self._param_nds[i]._data is not param_arrays[i]:
                    aux_updates[i] = self._param_nds[i]._data
            return out, aux_updates
        finally:
            for nd_, old, ver in saved:
                nd_._data = old
                nd_._version = ver
            block_mod._TRACING.flag = False
            _random.pop_trace_key(prev_key)  # mxlint: trace-pure — see push

    def _traced_update(self, weights, grads, states, t, lr):
        return _traced_update(self._optimizer, self._params[0].list_ctx()[0],
                              self._trainable, weights, grads, states, t, lr)

    # -- executable identity (ShardedTrainer overrides) -----------------
    def _step_key(self, sig):
        """Cache key for the fused step at one batch signature. The base
        trainer's fingerprint is a process-local instance token, so the
        key is quarantined from the persistent tier (no_persist);
        ShardedTrainer substitutes a stable cross-process fingerprint +
        topology and drops the quarantine."""
        from .. import compile as _compile

        return _compile.ExecutableKey("dist_step", self._compile_token,
                                      shapes=sig, sharded=True,
                                      donation=(3, 4), no_persist=True)

    def _forward_key(self, sig):
        from .. import compile as _compile

        return _compile.ExecutableKey("dist_forward", self._compile_token,
                                      shapes=sig, sharded=True,
                                      no_persist=True)

    def _resolve(self, key, build, **kw):
        """Registry resolution hook: ShardedTrainer brackets this with
        manifest prefetch/record so its fills land in a warmup manifest."""
        from .. import compile as _compile

        return _compile.get_or_build(key, build, **kw)

    def _build_step(self, batch_shapes):
        import jax
        import jax.numpy as jnp

        trainable, aux = self._trainable, self._aux
        loss_blk = self._loss

        amp = self._amp_dtype

        def maybe_cast(a):
            if amp is not None and jnp.issubdtype(a.dtype, jnp.floating):
                return a.astype(amp)
            return a

        def step(key, t, lr, arrays, states, *batch):
            train_arrays = [arrays[i] for i in trainable]
            other = list(arrays)

            def loss_fn(train_arrs):
                full = list(other)
                for k, i in enumerate(trainable):
                    # cast INSIDE the grad closure: the cast's vjp returns
                    # fp32 cotangents, i.e. grads accumulate at full precision
                    full[i] = maybe_cast(train_arrs[k])
                fwd_in = batch[:-1] if loss_blk is not None else batch
                fwd_in = tuple(maybe_cast(b) for b in fwd_in)
                out, aux_up = self._trace_forward(fwd_in, full, key, True)
                pred = out[0] if isinstance(out, (list, tuple)) else out
                # aux states (BatchNorm stats) keep their stored dtype
                aux_up = {i: u.astype(arrays[i].dtype)
                          for i, u in aux_up.items()}
                if loss_blk is not None:
                    # mxlint: trace-pure — per-trainer statics: the params'
                    # ctx and the loss-input mode deliberately specialize
                    # this executable (fixed for the trainer's lifetime)
                    label_nd = pred.__class__(batch[-1],
                                              ctx=self._params[0].list_ctx()[0])
                    mode = self._loss_inputs  # mxlint: trace-pure — see above
                    if mode is None:
                        # default: gluon loss Blocks keep the (pred, label)
                        # contract; plain callables see the whole output so
                        # auxiliary terms (MoE load-balance/z-loss, deep
                        # supervision heads) can fold into the objective.
                        # Pass loss_inputs="pred" to pin the old behavior.
                        from ..gluon.loss import Loss as _GluonLoss
                        mode = ("pred" if isinstance(loss_blk, _GluonLoss)
                                else "outputs")
                    if (mode == "outputs"
                            and isinstance(out, (list, tuple))
                            and len(out) > 1):
                        l = loss_blk(tuple(out), label_nd)
                    else:
                        l = loss_blk(pred, label_nd)
                    lval = jnp.mean(l._data.astype(jnp.float32))
                else:
                    lval = jnp.mean(pred._data.astype(jnp.float32))
                return lval, aux_up

            (loss_val, aux_up), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(train_arrays)
            new_w, new_s = self._traced_update(train_arrays, list(grads),
                                               states, t, lr)
            new_arrays = list(arrays)
            for k, i in enumerate(trainable):
                new_arrays[i] = new_w[k]
            for i in aux:
                if i in aux_up:
                    new_arrays[i] = aux_up[i]
            return loss_val, new_arrays, new_s

        from jax.sharding import PartitionSpec

        data_sh = [named_sharding(self._mesh, batch_spec(self._mesh, len(s)))
                   for s in batch_shapes]
        repl = named_sharding(self._mesh, PartitionSpec())
        out_shardings = (repl, list(self._shardings), list(self._state_shardings))
        # NOTE: donated buffers make a post-hoc lower() on live args
        # unsafe-looking but fine — lower() only traces avals, it never
        # executes or donates; cost analysis (now at the registry fill
        # hook, mxnet_tpu.compile.registry) happens on abstract values
        return jax.jit(
            step,
            in_shardings=(repl, repl, repl, list(self._shardings),
                          list(self._state_shardings), *data_sh),
            out_shardings=out_shardings,
            donate_argnums=(3, 4),
        )

    # ------------------------------------------------------------------
    def step(self, data, label=None, batch_size=None):
        """One synchronous sharded training step; returns the (replicated)
        scalar loss as an NDArray. Reference semantics: trainer.py:298
        step = allreduce + update, here fused into one executable."""
        import jax.numpy as jnp

        from .. import random as _random
        from ..ndarray import NDArray

        import time as _time

        t0 = _time.perf_counter()
        from .. import telemetry as _telemetry

        _telemetry.goodput.step_start(kind="dist", t0=t0)
        if self._loss is not None and label is None:
            raise MXNetError("this trainer was built with a loss that takes "
                             "(pred, label); step() needs a label argument")
        batch = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                 for a in ([data] if label is None else [data, label])]
        # the step's loss is jnp.mean over the (global) batch, so gradients
        # are already batch-means — unlike gluon.Trainer.step, which divides
        # summed grads by batch_size via rescale_grad. Leave rescale at the
        # optimizer's own value.

        sig = tuple((tuple(b.shape), str(b.dtype)) for b in batch)
        from .. import telemetry

        # the step's RNG key is minted BEFORE the executable fill: the AOT
        # lower below traces _trace_forward, and the global RNG chain must
        # be initialized eagerly — a lazy first _get() inside a trace would
        # store a tracer into process state (UnexpectedTracerError later)
        key = _random.next_key()
        # aval-only example args (ShapeDtypeStruct — committed host arrays
        # would fail the lower's sharding validation), passed as a THUNK
        # so a steady-state step pays nothing: on a true fill they let the
        # registry capture memory_analysis figures and run the donation
        # verifier on the fused step (telemetry.memory,
        # docs/observability.md §Memory)
        def example_avals():
            import jax

            aval = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
            return (aval(key), jax.ShapeDtypeStruct((), "float32"),
                    jax.ShapeDtypeStruct((), "float32"),
                    [aval(a) for a in self._arrays],
                    jax.tree_util.tree_map(aval, list(self._states)),
                    *[aval(b) for b in batch])

        fn = self._resolve(
            self._step_key(sig),
            lambda: self._build_step([b.shape for b in batch]),
            label="dist_trainer_step",
            example_args=example_avals,
            on_fill=lambda: telemetry.counter(
                "mxtpu_executor_build_total", {"what": "dist_step"}).inc(),
            event_fields={"batch_sig": str(sig)})

        with _telemetry.goodput.phase("data_wait"):
            batch = [self._shard_batch(b) for b in batch]
        # host-side schedule: the real step count advances here (only after
        # the batch sharded successfully, so a failed step doesn't skew the
        # update schedule); the traced update consumes it (and the scheduled
        # lr) as device scalars
        self._step_count += 1
        o = self._optimizer
        o.num_update = max(self._step_count + o.begin_num_update, o.num_update)
        lr = self._host_lr()
        t = jnp.asarray(self._step_count, dtype=jnp.float32)
        from .. import telemetry

        with telemetry.tracing.root("train.step", component="train",
                                    attrs={"step": self._step_count,
                                           "kind": "dist"}):
            telemetry.goodput.mark_launch()
            with telemetry.tracing.span("train.fused_step"), \
                    telemetry.goodput.phase("compute"):
                loss_val, self._arrays, self._states = fn(
                    key, t, jnp.asarray(lr, dtype=jnp.float32),
                    self._arrays, self._states, *batch)
            ctx = self._params[0].list_ctx()[0]
            # global-batch examples/sec: the leading dim of the (global)
            # batch
            examples = None
            if batch and getattr(batch[0], "ndim", 0) > 0:
                examples = int(batch[0].shape[0])
            telemetry.observe_step(_time.perf_counter() - t0,
                                   examples=examples,
                                   step=self._step_count, kind="dist")
            telemetry.goodput.step_end(step=self._step_count)
        from . import resilience

        # step-boundary fault hook (no-op unless MXTPU_FAULT_INJECT is set)
        resilience.maybe_inject_fault(self._step_count)
        return NDArray(loss_val, ctx=ctx)

    def _shard_batch(self, arr):
        import jax

        return jax.device_put(arr, named_sharding(
            self._mesh, batch_spec(self._mesh, arr.ndim)))

    def prefetch(self, it, depth=None):
        """Wrap a data iterator in a `data.DevicePrefetcher` bound to this
        trainer's mesh: batches arrive on-device already laid out as
        `batch_spec` shardings, so step()'s `_shard_batch` is a no-op and
        the host→device copy overlaps the previous step's compute
        (docs/data_pipeline.md)."""
        from ..data import DevicePrefetcher

        return DevicePrefetcher(it, depth=depth, mesh=self._mesh,
                                src="sharded")

    def forward(self, data, is_train=False):
        """Compiled sharded inference over the mesh."""
        import jax
        import jax.numpy as jnp

        from .. import random as _random
        from ..ndarray import NDArray

        x = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        # minted before the fill: the AOT lower must never initialize the
        # RNG chain inside its trace (see step())
        key = _random.next_key()
        sig = (tuple(x.shape), str(x.dtype), is_train)
        entry = self._fwd_compiled.get(sig)
        if entry is None:
            aux_order = []   # aux indices whose updates the trace emits
                             # (filled at trace time; stable thereafter)

            def build():
                def fwd(key, arrays, batch):
                    out, aux_up = self._trace_forward((batch,), arrays, key,
                                                      is_train)
                    pred = out[0] if isinstance(out, (list, tuple)) else out
                    # mxlint: trace-pure — aux_order is the trace's own
                    # output-ordering record (see decl above): filled once at
                    # trace time, read eagerly after resolve, stable after
                    aux_order.clear()
                    aux_order.extend(sorted(aux_up))  # mxlint: trace-pure
                    return pred._data, [aux_up[i] for i in aux_order]

                from jax.sharding import PartitionSpec

                return jax.jit(fwd, in_shardings=(
                    named_sharding(self._mesh, PartitionSpec()),
                    list(self._shardings),
                    named_sharding(self._mesh, batch_spec(self._mesh, x.ndim))))

            fn = self._resolve(
                self._forward_key(sig),
                build, label="dist_trainer_forward",
                example_args=lambda: (
                    jax.ShapeDtypeStruct(key.shape, key.dtype),
                    [jax.ShapeDtypeStruct(a.shape, a.dtype)
                     for a in self._arrays],
                    jax.ShapeDtypeStruct(x.shape, x.dtype)))
            entry = (fn, aux_order)
            self._fwd_compiled[sig] = entry
        fn, aux_order = entry
        out, aux_new = fn(key, self._arrays, self._shard_batch(x))
        # train-mode forward advances BatchNorm running stats (gluon
        # semantics); write the updates back into the mesh param set
        for i, arr in zip(aux_order, aux_new):
            self._arrays[i] = jax.device_put(arr, self._shardings[i])
        ctx = self._params[0].list_ctx()[0]
        return NDArray(out, ctx=ctx)

    # ------------------------------------------------------------------
    def sync_params(self):
        """Copy trained values back into the block's Parameters (for
        save_parameters/export — reference checkpoint flow §5.4)."""
        import jax

        for p, nd_, arr in zip(self._params, self._param_nds, self._arrays):
            host = np.asarray(jax.device_get(arr))
            p.set_data(nd_.__class__(host, ctx=p.list_ctx()[0]))
            nd_._data = p.data(p.list_ctx()[0])._data

    def save_checkpoint(self, directory, step=0):
        """Sharded checkpoint of parameters + optimizer state via orbax
        (tensorstore-backed). SURVEY §5.4: the reference's formats are
        single-file rank-0 writes; on a pod each host writes only its
        addressable shards, and restore re-shards onto the current mesh —
        no full gather through one host. Reference analogue:
        Trainer.save_states (trainer.py:429) + save_checkpoint
        (model.py:394)."""
        import os

        import orbax.checkpoint as ocp

        import jax

        path = os.path.abspath(os.fspath(directory))
        # optimizer states are arbitrary pytrees; store them as flat leaf
        # dicts (orbax normalizes tuple/list containers) and unflatten with
        # the live treedef on restore
        states = {}
        for i, st in zip(self._trainable, self._states):
            leaves = jax.tree_util.tree_leaves(st)
            states[str(i)] = {str(j): leaf for j, leaf in enumerate(leaves)}
        tree = {
            "params": dict(zip(self._param_names, self._arrays)),
            "states": states,
            "meta": {"step": self._step_count,
                     "num_update": self._optimizer.num_update},
        }
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(os.path.join(path, "step_%08d" % step), tree,
                       force=True)

    def load_checkpoint(self, directory, step=0):
        """Restore a save_checkpoint directory, placing every array directly
        onto its mesh sharding (each host reads only its shards)."""
        import os

        import jax
        import orbax.checkpoint as ocp

        path = os.path.join(os.path.abspath(os.fspath(directory)),
                            "step_%08d" % step)

        param_args = {n: ocp.ArrayRestoreArgs(sharding=sh)
                      for n, sh in zip(self._param_names, self._shardings)}
        state_args = {}
        for i, shs in zip(self._trainable, self._state_shardings):
            leaves = jax.tree_util.tree_leaves(shs)
            state_args[str(i)] = {
                str(j): ocp.ArrayRestoreArgs(sharding=sh)
                for j, sh in enumerate(leaves)}
        with ocp.PyTreeCheckpointer() as ckptr:
            restored = ckptr.restore(
                path,
                restore_args={
                    "params": param_args,
                    "states": state_args,
                    "meta": {"step": ocp.RestoreArgs(),
                             "num_update": ocp.RestoreArgs()},
                })
        self._arrays = [restored["params"][n] for n in self._param_names]
        new_states = []
        for i, st in zip(self._trainable, self._states):
            treedef = jax.tree_util.tree_structure(st)
            flat = restored["states"][str(i)]
            leaves = [flat[str(j)] for j in range(len(flat))]
            new_states.append(jax.tree_util.tree_unflatten(treedef, leaves))
        self._states = new_states
        self._step_count = int(restored["meta"]["step"])
        self._optimizer.num_update = int(restored["meta"]["num_update"])

    def save_states(self, fname):
        import pickle

        import jax

        from ..base import atomic_writer

        states = _tree_map(lambda a: np.asarray(jax.device_get(a)),
                           self._states)
        # atomic (temp + fsync + rename): a preempted pod mid-save keeps the
        # previous complete states file intact (parallel/resilience.py)
        with atomic_writer(fname, "wb") as f:
            pickle.dump({"states": states, "step": self._step_count,
                         "num_update": self._optimizer.num_update}, f)

    def load_states(self, fname):
        import pickle

        import jax

        with open(fname, "rb") as f:
            blob = pickle.load(f)
        self._step_count = blob["step"]
        self._optimizer.num_update = blob["num_update"]
        loaded = blob["states"]
        self._states = [
            _tree_map(lambda a, sh: jax.device_put(a, sh), st, shs)
            for st, shs in zip(loaded, self._state_shardings)]

    # -- per-rank sharded checkpoints (parallel.resilience format) ---------
    def shard_snapshot(self):
        """Host snapshot of THIS process's shards of every parameter and
        optimizer-state leaf — the only work the training thread pays on
        the async checkpoint path. Each array is recorded as its global
        shape/dtype plus the addressable pieces keyed by normalized
        (start, stop)-per-dim index, so `_install_shard_payloads` can
        either place pieces directly (same topology) or reassemble the
        global array and reshard it (elastic resume). Replicated shards
        (identical index on several local devices) are deduplicated."""
        import jax

        def entry(arr):
            shape = tuple(int(d) for d in arr.shape)
            pieces, seen = [], set()
            for s in arr.addressable_shards:
                key = tuple(sl.indices(dim)[:2]
                            for sl, dim in zip(s.index, shape))
                if key in seen:
                    continue
                seen.add(key)
                # np.array (copy), NOT np.asarray: on the CPU backend
                # device_get is zero-copy, and the fused step DONATES these
                # buffers — a view would dangle the moment the next step
                # runs, corrupting (or segfaulting) the background write
                pieces.append((key, np.array(jax.device_get(s.data))))
            return {"shape": shape, "dtype": str(arr.dtype),
                    "pieces": pieces}

        return {
            "params": {n: entry(a)
                       for n, a in zip(self._param_names, self._arrays)},
            "states": [[entry(leaf)
                        for leaf in jax.tree_util.tree_leaves(st)]
                       for st in self._states],
            "step": self._step_count,
            "num_update": self._optimizer.num_update,
        }

    def _install_shard_payloads(self, payloads, header):
        """`CheckpointManager.restore_sharded` loader: install parameters,
        optimizer state and the step/num_update cursors from shard
        payloads. Fast path gets only this rank's payload and places each
        piece verbatim; the elastic path gets EVERY saved shard,
        reassembles each global array (erroring on coverage holes) and
        reshards it onto the current mesh via make_array_from_callback —
        each process materializes only its addressable indices."""
        import jax
        import jax.numpy as jnp

        def materialize(entries, sharding, what):
            shape = tuple(entries[0]["shape"])
            dtype = entries[0]["dtype"]
            pieces = {}
            for e in entries:
                if tuple(e["shape"]) != shape or e["dtype"] != dtype:
                    raise MXNetError(
                        "sharded checkpoint: %s changed shape/dtype "
                        "(saved %r/%s, shard disagrees with %r/%s)"
                        % (what, tuple(e["shape"]), e["dtype"], shape,
                           dtype))
                for key, data in e["pieces"]:
                    pieces[tuple(tuple(p) for p in key)] = data
            cache = {}

            def full():
                if "a" not in cache:
                    out = np.zeros(shape, dtype)
                    cover = np.zeros(shape, bool)
                    for key, data in pieces.items():
                        slc = tuple(slice(a, b) for a, b in key)
                        out[slc] = data
                        cover[slc] = True
                    if not cover.all():
                        raise MXNetError(
                            "sharded checkpoint: the shard set does not "
                            "cover %s — an elastic resume needs every "
                            "saved rank's shard (a solo emergency "
                            "checkpoint only covers fully-replicated "
                            "state)" % what)
                    cache["a"] = out
                return cache["a"]

            def cb(index):
                key = tuple(sl.indices(dim)[:2]
                            for sl, dim in zip(index, shape))
                hit = pieces.get(key)
                piece = hit if hit is not None else full()[index]
                # hand jax an XLA-OWNED device array, never the raw
                # pickle-loaded numpy buffer: the CPU client zero-copies
                # 64-byte-aligned host memory, and these arrays feed the
                # DONATING fused step — donating a buffer numpy still owns
                # corrupts the heap (flaky SIGSEGV in whatever allocates
                # next, only in resumed generations)
                return jnp.array(piece, copy=True)

            return jax.make_array_from_callback(shape, sharding, cb)

        plist = list(payloads.values())
        new_arrays = []
        for name, sh in zip(self._param_names, self._shardings):
            entries = [p["params"].get(name) for p in plist]
            if any(e is None for e in entries):
                raise MXNetError(
                    "sharded checkpoint: parameter %r missing from a "
                    "shard — the checkpoint was saved for a different "
                    "model" % name)
            new_arrays.append(materialize(entries, sh, "param %r" % name))
        new_states = []
        for k, (st, shs) in enumerate(zip(self._states,
                                          self._state_shardings)):
            per_payload = [p["states"][k] for p in plist]
            sh_leaves = jax.tree_util.tree_leaves(shs)
            n = len(sh_leaves)
            if any(len(pp) != n for pp in per_payload):
                raise MXNetError(
                    "sharded checkpoint: optimizer state %d leaf count "
                    "mismatch — saved with a different optimizer" % k)
            leaves = [materialize([pp[j] for pp in per_payload],
                                  sh_leaves[j], "state[%d][%d]" % (k, j))
                      for j in range(n)]
            new_states.append(jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(st), leaves))
        self._arrays = new_arrays
        self._states = new_states
        self._step_count = int(plist[0]["step"])
        self._optimizer.num_update = int(plist[0]["num_update"])

    def _shard_identity(self):
        import jax

        from .mesh import mesh_fingerprint

        return (jax.process_index(), jax.process_count(),
                mesh_fingerprint(self._mesh))

    def save_sharded_checkpoint(self, manager, step=None, meta=None):
        """Write this rank's shard of a sharded checkpoint through
        `manager` (parallel.resilience.CheckpointManager): snapshot on the
        calling thread, serialize+fsync+manifest-publish on the manager's
        background writer (MXTPU_CKPT_ASYNC). Every rank must call this at
        the same step boundary."""
        rank, world, topology = self._shard_identity()
        return manager.save_sharded_async(
            self._step_count if step is None else step,
            self.shard_snapshot(), rank=rank, world_size=world,
            topology=topology, meta=meta)

    def emergency_sharded_checkpoint(self, manager, meta=None):
        """SOLO synchronous checkpoint for the preemption path: flush any
        in-flight async save, then publish this rank's snapshot as a
        1-shard manifest (rank 0 of world 1) with no peer cooperation —
        the preempting agent only notified THIS rank, and the others may
        be wedged in a collective. Restoring it at any world size goes
        through the elastic path; it covers the full model whenever this
        process's shards do (pure data-parallel / single-host — a
        cross-process-partitioned model needs a group-wide `preempt`
        instead, and restore errors honestly on the coverage hole)."""
        _, _, topology = self._shard_identity()
        manager.flush()
        m = dict(meta or {})
        m.setdefault("preempt", True)
        return manager.save_sharded(
            self._step_count, self.shard_snapshot(), rank=0, world_size=1,
            topology=topology, meta=m)

    def restore_sharded_checkpoint(self, manager, step=None):
        """Restore the newest complete sharded checkpoint (or `step`) onto
        the CURRENT mesh; reshards when the saved topology/world size
        differs (the compile key's topology fingerprint then honestly
        misses once). Returns the manifest header, or None when there is
        nothing to restore."""
        rank, world, topology = self._shard_identity()
        return manager.restore_sharded(
            self._install_shard_payloads, step=step, rank=rank,
            world_size=world, topology=topology)
