"""Device mesh management — the TPU-native replacement for the reference's
device-list/ps-topology plumbing (kvstore.cc:40-72 transport selection,
tools/launch.py rendezvous).

Instead of a list of `mx.gpu(i)` contexts plus a kvstore transport, the unit
of scale is a `jax.sharding.Mesh` with named axes. Conventional axis names:

    dp — data parallel (batch dimension)
    fsdp — fully-sharded data parallel (params sharded over the data axis)
    tp — tensor/model parallel (hidden dimension)
    pp — pipeline parallel (layer stages)
    sp — sequence/context parallel (ring attention)
    ep — expert parallel (MoE)

Collectives ride ICI when the mesh axes follow the physical torus; XLA
handles DCN hierarchy across pod slices (SURVEY §5.8 TPU-equivalent note).
"""
from __future__ import annotations

import contextlib
import math
import threading

import numpy as np

__all__ = [
    "make_mesh", "default_mesh", "current_mesh", "use_mesh", "local_devices",
    "mesh_fingerprint",
    "DP", "FSDP", "TP", "PP", "SP", "EP",
]

DP, FSDP, TP, PP, SP, EP = "dp", "fsdp", "tp", "pp", "sp", "ep"

_state = threading.local()


def local_devices(platform=None):
    """Devices addressable by THIS process (host-local, for data placement)."""
    import jax

    return [d for d in jax.local_devices()
            if platform is None or d.platform == platform]


def make_mesh(axes=None, devices=None):
    """Create a `jax.sharding.Mesh`.

    `axes` is an ordered dict / list of (name, size) pairs; a size of -1
    means "whatever is left" (at most one). With no axes, the mesh is 1-D
    data-parallel over every visible device — the moral equivalent of the
    reference's default `ctx=[mx.gpu(i) for i in ...]` + kvstore('device').
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axes is None:
        axes = [(DP, n)]
    if isinstance(axes, dict):
        axes = list(axes.items())
    names = [a for a, _ in axes]
    sizes = [s for _, s in axes]
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if math.prod(sizes) != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs "
                         f"{math.prod(sizes)} devices, have {n}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def mesh_fingerprint(mesh):
    """Device-topology fingerprint of a mesh: named axes x shape x sorted
    device kinds x process count, as one deterministic string (e.g.
    ``dp=2,tp=4|cpu|procs=1``). This is the `ExecutableKey.topology`
    component that lets SHARDED executables reach the persistent compile
    cache honestly: a serialized sharded step deserializes only onto the
    same mesh geometry and device fleet it was compiled for, so the
    fingerprint rides the artifact digest — same topology across a restart
    hits, any other topology is a clean miss (docs/compile_cache.md)."""
    import jax

    axes = ",".join("%s=%d" % (str(n), int(s))
                    for n, s in zip(mesh.axis_names, mesh.devices.shape))
    devices = list(mesh.devices.flat)
    kinds = sorted({str(getattr(d, "device_kind", None) or d.platform)
                    for d in devices})
    return "%s|%s|procs=%d" % (axes, "+".join(kinds), jax.process_count())


def default_mesh():
    """The process-wide default mesh (1-D data parallel over all devices)."""
    m = getattr(_state, "default", None)
    if m is None:
        m = make_mesh()
        _state.default = m
    return m


def current_mesh():
    return getattr(_state, "current", None) or default_mesh()


@contextlib.contextmanager
def use_mesh(mesh):
    """Scope a mesh as the current one (analogous to the reference's
    Context stack, context.py:87)."""
    prev = getattr(_state, "current", None)
    _state.current = mesh
    try:
        yield mesh
    finally:
        _state.current = prev
