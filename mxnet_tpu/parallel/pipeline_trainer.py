"""PipelineTrainer — pipeline-parallel training of real Gluon models.

The reference has no pipeline parallelism (SURVEY §2.3); `pipeline.py`
provides the collective GPipe loop for uniform stages. This module lifts
its constraints so an actual model — the in-tree BERT encoder stack — can
be pipelined through the Gluon API:

  * **non-uniform ends**: the embedding front (`prelude`) and the
    pooler/head back (`postlude`) run replicated on every pp device
    outside the loop; only the uniform transformer-layer stack is
    pipelined. For transformer models the ends are a few percent of the
    FLOPs, so replicating them costs almost nothing while removing the
    shape-preservation constraint where it doesn't hold.
  * **Gluon params, not hand-stacked pytrees**: the trainer collects each
    layer's Parameters, verifies the stack is homogeneous, and stacks
    them into (pp, layers_per_stage, ...) leaves sharded over the `pp`
    mesh axis — one stage's slice resident per device. `sync_params()`
    unstacks trained values back into the Blocks for save/export.
  * **one executable**: prelude → pipelined stack → postlude → loss →
    backward → optimizer update compile into a single donated-buffer XLA
    program, like DistributedTrainer. Any registered optimizer works
    (elementwise updates apply per stacked leaf).
  * **microbatch schedule control**: `num_microbatches` sets pipeline
    depth utilization (bubble fraction = (pp-1)/(m+pp-1));
    `remat=True` bounds live activations to stage inputs (the 1F1B
    peak-memory behavior, achieved functionally — pipeline.py docstring).

Masks (BERT `valid_length`) travel with their microbatch as pipeline
`extras`. A dp axis in the mesh composes: batch dims shard over dp while
stages shard over pp.

Usage (model side: BERTModel.pipeline_stages() — transformer.py):

    mesh = make_mesh([("pp", 4)])
    trainer = PipelineTrainer(model, "adam", {"learning_rate": 1e-4},
                              loss=SoftmaxCrossEntropyLoss(), mesh=mesh)
    loss = trainer.step(tokens, labels)
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .. import optimizer as opt_mod
from .mesh import PP, current_mesh
from .pipeline import pipeline_apply
from .sharding import batch_spec, named_sharding
from .trainer import _host_lr, _traced_update, _tree_map

__all__ = ["PipelineTrainer"]


class PipelineTrainer:
    """Compiled pipeline-parallel training over the `pp` mesh axis.

    Parameters
    ----------
    block : gluon.Block — initialized. Must either implement
        ``pipeline_stages() -> (prelude, cells, postlude)`` (see
        BERTModel.pipeline_stages) or be accompanied by explicit
        `cells`/`prelude`/`postlude` arguments.
    optimizer : str or Optimizer
    optimizer_params : dict
    loss : gluon loss Block / callable(pred, label) -> per-sample loss
    cells : list of homogeneous HybridBlocks to pipeline (len divisible
        by the pp axis size); default block.pipeline_stages()[1]
    prelude : callable(*inputs) -> activation NDArray, or
        (activation, mask) pair; runs replicated before the pipeline.
        Default: identity on a single input.
    postlude : callable(activation NDArray) -> prediction NDArray (or
        tuple whose first element is the prediction); replicated after.
    mesh : jax.sharding.Mesh with a `pp` axis (default current_mesh())
    num_microbatches : int (default: pipeline depth)
    remat : bool — recompute stage interiors in backward (memory-optimal)
    amp_dtype : bf16 compute with fp32 master weights, as in
        DistributedTrainer
    """

    def __init__(self, block, optimizer, optimizer_params=None, loss=None,
                 cells=None, prelude=None, postlude=None, mesh=None,
                 axis_name=PP, num_microbatches=None, remat=False,
                 amp_dtype=None):
        import jax

        self._block = block
        self._mesh = mesh or current_mesh()
        self._axis = axis_name
        if axis_name not in self._mesh.shape:
            raise MXNetError("mesh has no '%s' axis (axes: %s)"
                             % (axis_name, tuple(self._mesh.shape)))
        self._pp = self._mesh.shape[axis_name]
        self._loss = loss
        self._amp_dtype = amp_dtype
        self._remat = remat

        if cells is None or prelude is None or postlude is None:
            if not hasattr(block, "pipeline_stages"):
                raise MXNetError(
                    "block does not implement pipeline_stages(); pass "
                    "cells=/prelude=/postlude= explicitly")
            d_pre, d_cells, d_post = block.pipeline_stages()
            cells = cells if cells is not None else d_cells
            prelude = prelude if prelude is not None else d_pre
            postlude = postlude if postlude is not None else d_post
        self._cells = list(cells)
        self._prelude = prelude or (lambda x: x)
        self._postlude = postlude or (lambda x: x)
        if len(self._cells) % self._pp:
            raise MXNetError("%d cells not divisible into %d pipeline "
                             "stages" % (len(self._cells), self._pp))
        self._cps = len(self._cells) // self._pp
        self._num_microbatches = num_microbatches

        # -- canonical per-cell parameter order; verify homogeneity --------
        def cell_items(cell):
            return sorted(cell.collect_params().items())

        first = cell_items(self._cells[0])
        self._cell_local_names = [self._strip(self._cells[0], n)
                                  for n, _ in first]
        sigs = []
        for cell in self._cells:
            items = cell_items(cell)
            sigs.append([(self._strip(cell, n), tuple(p.shape),
                          np.dtype(p.dtype).name, p.grad_req)
                         for n, p in items])
        if any(s != sigs[0] for s in sigs[1:]):
            raise MXNetError(
                "pipeline cells are not homogeneous (same local param "
                "names/shapes/dtypes required): %s vs %s"
                % (sigs[0], next(s for s in sigs if s != sigs[0])))
        if any(req == "null" for _, _, _, req in sigs[0]):
            raise MXNetError("pipeline cells with aux (grad_req='null') "
                             "state are not supported — running stats "
                             "cannot be carried through the stage loop")

        ctx = None
        all_items = sorted(block.collect_params().items())
        if not all_items:
            raise MXNetError("block has no parameters; initialize() it first")
        ctx = all_items[0][1].list_ctx()[0]
        self._ctx = ctx

        # -- split params: pipelined cell leaves vs outer (ends) -----------
        cell_param_names = set()
        self._cell_nds = []       # [cell][j] NDArray view, canonical order
        for cell in self._cells:
            items = cell_items(cell)
            cell_param_names.update(n for n, _ in items)
            self._cell_nds.append([p.data(ctx) for _, p in items])

        outer_items = [(n, p) for n, p in all_items
                       if n not in cell_param_names]
        self._outer_names = [n for n, _ in outer_items]
        self._outer_params = [p for _, p in outer_items]
        self._outer_nds = [p.data(ctx) for p in self._outer_params]
        self._outer_trainable = [i for i, p in enumerate(self._outer_params)
                                 if p.grad_req != "null"]
        self._outer_aux = [i for i, p in enumerate(self._outer_params)
                          if p.grad_req == "null"]

        # -- stacked cell leaves on the mesh: (pp, cps, *shape) ------------
        from jax.sharding import PartitionSpec as P

        self._pp_sharding = named_sharding(self._mesh, P(axis_name))
        self._repl = named_sharding(self._mesh, P())
        self._cell_leaves = []
        for j in range(len(first)):
            stacked = np.stack([np.asarray(jax.device_get(
                self._cell_nds[c][j]._data)) for c in range(len(self._cells))])
            stacked = stacked.reshape((self._pp, self._cps)
                                      + stacked.shape[1:])
            self._cell_leaves.append(
                jax.device_put(stacked, self._pp_sharding))

        # fresh device-side copy so the mesh array NEVER aliases the
        # block's live param buffer: device_put can reuse a matching shard
        # in place, and the step's buffer donation would then delete the
        # param out from under the block (breaking later eager use / a
        # second trainer)
        import jax.numpy as jnp

        self._outer_arrays = [
            jax.device_put(jnp.array(nd_._data, copy=True), self._repl)
            for nd_ in self._outer_nds]

        # -- optimizer + state (outer trainables then cell leaves) ---------
        optimizer_params = optimizer_params or {}
        if isinstance(optimizer, opt_mod.Optimizer):
            self._optimizer = optimizer
        else:
            self._optimizer = opt_mod.create(optimizer, **optimizer_params)

        from ..ndarray import NDArray

        self._states = []
        self._state_shardings = []
        self._weight_keys = ([("outer", i) for i in self._outer_trainable]
                             + [("cell", j)
                                for j in range(len(self._cell_leaves))])
        for k, (kind, i) in enumerate(self._weight_keys):
            if kind == "outer":
                w_nd, sh = self._outer_nds[i], self._repl
            else:
                w_nd = NDArray(self._cell_leaves[i], ctx=ctx)
                sh = self._pp_sharding
            st = self._optimizer.create_state_multi_precision(k, w_nd)
            self._states.append(_tree_map(
                lambda s: jax.device_put(s._data, sh), st))
            self._state_shardings.append(_tree_map(lambda s: sh, st))

        self._step_count = 0
        # executables resolve through mxnet_tpu.compile, keyed by this
        # process-local token x batch signature (memory tier only)
        from .. import compile as _compile

        self._compile_token = _compile.instance_token("PipelineTrainer")

    # ------------------------------------------------------------------
    @staticmethod
    def _strip(cell, name):
        pre = cell.prefix
        return name[len(pre):] if name.startswith(pre) else name

    @property
    def optimizer(self):
        return self._optimizer

    @property
    def mesh(self):
        return self._mesh

    def _host_lr(self):
        return _host_lr(self._optimizer)

    # ------------------------------------------------------------------
    def _swap_all(self, outer_arrays):
        """Swap the outer (prelude/postlude) param buffers for traced
        arrays; cell buffers are swapped per-layer in _call_cell."""
        saved = [(nd_, nd_._data, nd_._version) for nd_ in self._outer_nds]
        for nd_, arr in zip(self._outer_nds, outer_arrays):
            nd_._data = arr
        return saved

    @staticmethod
    def _restore(saved):
        for nd_, old, ver in saved:
            nd_._data = old
            nd_._version = ver

    def _call_cell(self, leaves, act, mask, key):
        """Apply ONE layer: swap the template cell's param buffers with
        `leaves` (this layer's arrays) and run its Gluon forward under a
        per-layer RNG key (decorrelated dropout across layers/stages)."""
        from .. import random as _random
        from ..ndarray import NDArray

        cell = self._cells[0]
        nds = self._cell_nds[0]
        saved = [(nd_, nd_._data, nd_._version) for nd_ in nds]
        prev_key = _random.push_trace_key(key)
        try:
            for nd_, arr in zip(nds, leaves):
                nd_._data = arr
            a_nd = NDArray(act, ctx=self._ctx)
            if mask is None:
                out = cell(a_nd)
            else:
                out = cell(a_nd, NDArray(mask, ctx=self._ctx))
            return out._data
        finally:
            self._restore(saved)
            _random.pop_trace_key(prev_key)

    def _stage_fn(self, stage_leaves, act, *extras):
        """One pipeline stage = scan over this stage's cps layers.

        extras = (mask?, sample_ids): sample_ids is a per-sample int32
        array riding with each microbatch; folding its first element into
        the RNG key decorrelates dropout across microbatches (the loop
        body is traced once, so a static key would repeat per tick)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from .. import random as _random

        mask = extras[0] if len(extras) == 2 else None
        ids = extras[-1]
        base = jax.random.fold_in(_random.next_key(), ids[0])
        sidx = lax.axis_index(self._axis)

        def layer_body(a, xs):
            per_layer_leaves, li = xs
            key = jax.random.fold_in(jax.random.fold_in(base, sidx), li)
            return self._call_cell(per_layer_leaves, a, mask, key), None

        act, _ = lax.scan(layer_body, act,
                          (stage_leaves, jnp.arange(self._cps)))
        return act

    # ------------------------------------------------------------------
    def _traced_update(self, weights, grads, states, t, lr):
        return _traced_update(self._optimizer, self._ctx,
                              list(range(len(self._weight_keys))),
                              weights, grads, states, t, lr)


    def _run_model(self, batch_arrays, outer_full, cell_leaves, key,
                   is_train):
        """prelude -> pipelined stack -> postlude, eager-traced (buffers
        swapped) so Gluon code builds the jax computation."""
        import jax.numpy as jnp

        from .. import autograd, random as _random
        from ..gluon import block as block_mod
        from ..ndarray import NDArray

        # mxlint: trace-pure — routes the traced step key through the
        # RNG chain for the trace's duration; restored in finally
        prev_key = _random.push_trace_key(key)
        saved = self._swap_all(outer_full)
        block_mod._TRACING.flag = True
        try:
            call_args = [NDArray(a, ctx=self._ctx) for a in batch_arrays]
            with autograd._scope(recording=False, training=is_train):
                pre = self._prelude(*call_args)
                if isinstance(pre, (tuple, list)):
                    act_nd, mask_nd = pre[0], pre[1]
                else:
                    act_nd, mask_nd = pre, None
                mask_arr = None if mask_nd is None else mask_nd._data
                ids = jnp.arange(act_nd.shape[0], dtype=jnp.int32)
                extras = (ids,) if mask_arr is None else (mask_arr, ids)

                act = pipeline_apply(
                    self._stage_fn, cell_leaves, act_nd._data,
                    num_microbatches=self._num_microbatches,
                    axis_name=self._axis, mesh=self._mesh,
                    extras=extras, remat=self._remat)

                out = self._postlude(NDArray(act, ctx=self._ctx))
            pred = out[0] if isinstance(out, (list, tuple)) else out
            aux_up = {}
            for i in self._outer_aux:
                if self._outer_nds[i]._data is not outer_full[i]:
                    aux_up[i] = self._outer_nds[i]._data
            return pred._data, aux_up
        finally:
            self._restore(saved)
            block_mod._TRACING.flag = False
            _random.pop_trace_key(prev_key)  # mxlint: trace-pure — see push

    def _build_step(self, batch_shapes):
        import jax
        import jax.numpy as jnp

        trainable = self._outer_trainable
        aux = self._outer_aux
        loss_blk = self._loss
        amp = self._amp_dtype
        n_outer_t = len(trainable)

        def maybe_cast(a):
            if amp is not None and jnp.issubdtype(a.dtype, jnp.floating):
                return a.astype(amp)
            return a

        def step(key, t, lr, outer_arrays, cell_leaves, states, *batch):
            outer_t = [outer_arrays[i] for i in trainable]

            def loss_fn(wl):
                outer_w, cell_w = wl[:n_outer_t], wl[n_outer_t:]
                full = list(outer_arrays)
                for k, i in enumerate(trainable):
                    full[i] = maybe_cast(outer_w[k])
                cells_amp = [maybe_cast(c) for c in cell_w]
                fwd_in = batch[:-1] if loss_blk is not None else batch
                fwd_in = tuple(maybe_cast(b) if jnp.issubdtype(
                    b.dtype, jnp.floating) else b for b in fwd_in)
                pred_arr, aux_up = self._run_model(fwd_in, full, cells_amp,
                                                   key, True)
                aux_up = {i: u.astype(outer_arrays[i].dtype)
                          for i, u in aux_up.items()}
                from ..ndarray import NDArray

                if loss_blk is not None:
                    # mxlint: trace-pure — self._ctx is frozen per-trainer
                    # config; a rebuilt trainer resolves a fresh executable
                    pred_nd = NDArray(pred_arr, ctx=self._ctx)
                    label_nd = NDArray(batch[-1], ctx=self._ctx)  # mxlint: trace-pure — ditto
                    l = loss_blk(pred_nd, label_nd)
                    lval = jnp.mean(l._data.astype(jnp.float32))
                else:
                    lval = jnp.mean(pred_arr.astype(jnp.float32))
                return lval, aux_up

            weights = outer_t + list(cell_leaves)
            (loss_val, aux_up), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(weights)
            new_w, new_s = self._traced_update(weights, list(grads),
                                               states, t, lr)
            new_outer = list(outer_arrays)
            for k, i in enumerate(trainable):
                new_outer[i] = new_w[k]
            for i in aux:
                if i in aux_up:
                    new_outer[i] = aux_up[i]
            new_cells = new_w[n_outer_t:]
            return loss_val, new_outer, new_cells, new_s

        data_sh = [named_sharding(self._mesh,
                                  batch_spec(self._mesh, len(s)))
                   for s in batch_shapes]
        out_shardings = (self._repl,
                         [self._repl] * len(self._outer_arrays),
                         [self._pp_sharding] * len(self._cell_leaves),
                         list(self._state_shardings))
        return jax.jit(
            step,
            in_shardings=(self._repl, self._repl, self._repl,
                          [self._repl] * len(self._outer_arrays),
                          [self._pp_sharding] * len(self._cell_leaves),
                          list(self._state_shardings), *data_sh),
            out_shardings=out_shardings,
            donate_argnums=(3, 4, 5),
        )

    # ------------------------------------------------------------------
    def step(self, *batch):
        """One pipelined training step over (inputs..., label); returns
        the scalar loss NDArray."""
        import jax.numpy as jnp

        from .. import random as _random
        from ..ndarray import NDArray

        import time as _time

        t0 = _time.perf_counter()
        from .. import telemetry as _telemetry

        _telemetry.goodput.step_start(kind="pipeline", t0=t0)
        if self._loss is not None and len(batch) < 2:
            raise MXNetError("step(*inputs, label) needs a label for the "
                             "configured loss")
        arrs = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                for a in batch]
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in arrs)
        from .. import compile as _compile

        # minted BEFORE the fill: the AOT lower below traces the model and
        # the RNG chain must never initialize inside a trace (trainer.py)
        key = _random.next_key()
        # aval-only example args as a thunk (see trainer.py): on a true
        # fill they let the registry capture memory_analysis figures and
        # run the donation verifier on the fused pipeline step
        def example_avals():
            import jax as _jax

            aval = lambda a: _jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
            return (aval(key), _jax.ShapeDtypeStruct((), "float32"),
                    _jax.ShapeDtypeStruct((), "float32"),
                    [aval(a) for a in self._outer_arrays],
                    [aval(a) for a in self._cell_leaves],
                    _jax.tree_util.tree_map(aval, list(self._states)),
                    *map(aval, arrs))

        fn = _compile.get_or_build(
            _compile.ExecutableKey("pipeline_step", self._compile_token,
                                   shapes=sig, sharded=True,
                                   donation=(3, 4, 5), no_persist=True),
            lambda: self._build_step([a.shape for a in arrs]),
            label="pipeline_trainer_step",
            example_args=example_avals)

        import jax

        with _telemetry.goodput.phase("data_wait"):
            arrs = [jax.device_put(a, named_sharding(
                self._mesh, batch_spec(self._mesh, a.ndim))) for a in arrs]
        self._step_count += 1
        o = self._optimizer
        o.num_update = max(self._step_count + o.begin_num_update,
                           o.num_update)
        lr = self._host_lr()
        t = jnp.asarray(self._step_count, dtype=jnp.float32)
        _telemetry.goodput.mark_launch()
        with _telemetry.goodput.phase("compute"):
            loss_val, self._outer_arrays, self._cell_leaves, self._states = \
                fn(key, t, jnp.asarray(lr, dtype=jnp.float32),
                   self._outer_arrays, self._cell_leaves, self._states,
                   *arrs)
        from .. import telemetry

        examples = int(arrs[0].shape[0]) if getattr(arrs[0], "ndim", 0) \
            else None
        telemetry.observe_step(_time.perf_counter() - t0, examples=examples,
                               step=self._step_count, kind="pipeline")
        _telemetry.goodput.step_end(step=self._step_count)
        return NDArray(loss_val, ctx=self._ctx)

    def forward(self, *batch, is_train=False):
        """Pipelined inference (for numerics checks vs the sequential
        model)."""
        import jax
        import jax.numpy as jnp

        from .. import random as _random
        from ..ndarray import NDArray

        arrs = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                for a in batch]
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in arrs) + (is_train,)

        def build():
            def fwd(key, outer_arrays, cell_leaves, *data):
                pred, _ = self._run_model(data, list(outer_arrays),
                                          list(cell_leaves), key, is_train)
                return pred

            data_sh = [named_sharding(self._mesh,
                                      batch_spec(self._mesh, a.ndim))
                       for a in arrs]
            return jax.jit(fwd, in_shardings=(
                self._repl, [self._repl] * len(self._outer_arrays),
                [self._pp_sharding] * len(self._cell_leaves), *data_sh))

        from .. import compile as _compile

        fn = _compile.get_or_build(
            _compile.ExecutableKey("pipeline_forward", self._compile_token,
                                   shapes=sig, sharded=True,
                                   no_persist=True),
            build, label="pipeline_trainer_forward")
        key = _random.next_key()
        arrs = [jax.device_put(a, named_sharding(
            self._mesh, batch_spec(self._mesh, a.ndim))) for a in arrs]
        out = fn(key, self._outer_arrays, self._cell_leaves, *arrs)
        return NDArray(out, ctx=self._ctx)

    # ------------------------------------------------------------------
    def sync_params(self):
        """Unstack trained leaves back into the Blocks' Parameters (for
        save_parameters/export — reference checkpoint flow §5.4)."""
        import jax

        for i, (p, nd_) in enumerate(zip(self._outer_params,
                                         self._outer_nds)):
            host = np.asarray(jax.device_get(self._outer_arrays[i]))
            p.set_data(nd_.__class__(host, ctx=p.list_ctx()[0]))
            nd_._data = p.data(p.list_ctx()[0])._data
        for j, leaf in enumerate(self._cell_leaves):
            host = np.asarray(jax.device_get(leaf))
            flat = host.reshape((len(self._cells),) + host.shape[2:])
            for c, cell in enumerate(self._cells):
                items = sorted(cell.collect_params().items())
                name, p = items[j]
                nd_ = self._cell_nds[c][j]
                p.set_data(nd_.__class__(flat[c], ctx=p.list_ctx()[0]))
                nd_._data = p.data(p.list_ctx()[0])._data
