"""Collective communication layer — XLA collectives over the mesh.

Replaces the reference's four transports (SURVEY §5.8): ps-lite/ZMQ
parameter server (kvstore_dist.h:44), NCCL (kvstore_nccl.h:285-482),
CommDevice P2P reduce (comm.h:451-728) and CommCPU (comm.h:272-407).
Inside a compiled step these are `lax.psum`/`all_gather`/`ppermute` which
XLA lowers onto ICI rings (and DCN across pod slices); at the host level
`jax.distributed` replaces the ps-lite scheduler rendezvous.

Two call modes:
  * inside `shard_map`/`pmap` — the `axis_name` forms are used directly;
  * outside jit — `all_reduce_arrays` provides an eager, engine-style
    reduce across per-device NDArray copies (what kvstore('device') uses).
"""
from __future__ import annotations

import time as _time_mod

from .. import env as _env
from ..telemetry import core as _telemetry
from ..telemetry import recorder as _recorder

__all__ = [
    "psum", "pmean", "pmax", "pmin", "all_gather", "reduce_scatter",
    "ppermute", "axis_index", "axis_size", "all_to_all",
    "all_reduce_arrays", "broadcast_arrays", "init_process_group", "barrier",
    "rank", "num_workers",
]


# ---- in-graph collectives (use inside shard_map-ped / pmapped fns) --------

def psum(x, axis_name):
    import jax

    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name):
    import jax

    return jax.lax.pmean(x, axis_name)


def pmax(x, axis_name):
    import jax

    return jax.lax.pmax(x, axis_name)


def pmin(x, axis_name):
    import jax

    return jax.lax.pmin(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    import jax

    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    import jax

    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)


def ppermute(x, axis_name, perm):
    import jax

    return jax.lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    import jax

    return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis,
                              tiled=tiled)


def axis_index(axis_name):
    import jax

    return jax.lax.axis_index(axis_name)


def axis_size(axis_name):
    import jax

    return jax.lax.psum(1, axis_name)


# ---- eager cross-device reduce (kvstore('device') backend) ----------------

def _payload_bytes(arrays):
    """Total bytes across a list of jax/np arrays (best-effort)."""
    total = 0
    for a in arrays:
        try:
            total += int(a.size) * int(a.dtype.itemsize)
        except (AttributeError, TypeError):
            pass
    return total


def _observe_collective(op, arrays, seconds):
    """Telemetry for one eager collective: call count, payload bytes, and
    dispatch latency (async enqueue time — profile_sync-style device timing
    belongs to the profiler, not the always-on layer)."""
    if not _telemetry._STATE.enabled:
        return  # the kill switch must also skip the payload-byte scan
    from ..telemetry import tracing as _tracing

    nbytes = _payload_bytes(arrays)
    labels = {"op": op}
    _telemetry.counter("mxtpu_collective_calls_total", labels).inc()
    _telemetry.counter("mxtpu_collective_bytes_total", labels).inc(nbytes)
    _telemetry.histogram("mxtpu_collective_seconds", labels).observe(
        seconds, exemplar=_tracing.current_trace_id())
    # inside a traced step, the collective becomes a child span (emitted
    # retroactively from the measured window; no-op otherwise)
    _tracing.emit_span("train.collective", _time_mod.time() - seconds,
                       seconds, _tracing.current(), component="train",
                       attrs={"op": op, "bytes": nbytes})


def all_reduce_arrays(arrays):
    """Sum a list of same-shaped jax arrays living on different devices and
    return the sum materialized on each array's device — the eager
    equivalent of CommDevice::Reduce+Broadcast (comm.h:451-728). XLA runs
    the adds on-device; transfers ride ICI when available."""
    import jax

    if not arrays:
        return []
    t0 = _time_mod.perf_counter()
    if len(arrays) == 1:
        out = [jax.device_put(arrays[0], list(arrays[0].devices())[0])]
        _observe_collective("all_reduce", arrays,
                            _time_mod.perf_counter() - t0)
        return out
    # pairwise tree reduce: log2(n) rounds of concurrent adds instead of a
    # serial hub chain (the comm.h:451-728 CommDevice analogue)
    level = list(arrays)
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            a, b = level[i], level[i + 1]
            nxt.append(a + jax.device_put(b, list(a.devices())[0]))
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
    total = level[0]
    out = [jax.device_put(total, list(a.devices())[0]) for a in arrays]
    _observe_collective("all_reduce", arrays, _time_mod.perf_counter() - t0)
    return out


def _barrier_sum(v):
    # module-level jitted reduction: jax.jit caches by function identity, so
    # a per-call lambda would retrace + recompile on every barrier()
    import jax

    global _BARRIER_JIT
    if _BARRIER_JIT is None:
        _BARRIER_JIT = jax.jit(lambda v: v.sum())
    return _BARRIER_JIT(v)


_BARRIER_JIT = None


def broadcast_arrays(src, devices):
    import jax

    t0 = _time_mod.perf_counter()
    out = [jax.device_put(src, d) for d in devices]
    _observe_collective("broadcast", [src] * len(out),
                        _time_mod.perf_counter() - t0)
    return out


# ---- multi-host bootstrap (ps-lite scheduler replacement) -----------------

def _enable_cpu_collectives(jax):
    """Multi-process groups on the CPU backend need an explicit cross-host
    collectives implementation — without one, every cross-process psum dies
    with XLA's 'Multiprocess computations aren't implemented on the CPU
    backend'. Select gloo when the platform is explicitly CPU (tests,
    localhost launches; MXTPU_CPU_COLLECTIVES overrides, 'none' disables).
    Must run before backend init, i.e. alongside the rendezvous."""
    import os

    impl = _env.get("MXTPU_CPU_COLLECTIVES")
    if impl == "none":
        return
    plats = (jax.config.jax_platforms or os.environ.get("JAX_PLATFORMS")
             or "")
    if "cpu" not in [p.strip() for p in plats.split(",")]:
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", impl)
    except Exception:
        pass  # config absent on this jax: keep the old single-process-only
        # behavior rather than failing the rendezvous


def _group_initialized(jax):
    """Is the jax.distributed client already up? `jax.distributed
    .is_initialized` only exists on newer jax; older releases (this image's
    0.4.37 included) expose the state via the module-level singleton. This
    gap made init_process_group raise on EVERY multi-process worker — the
    five seed test_dist_kvstore failures."""
    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    try:
        from jax._src import distributed as _dist

        state = getattr(_dist, "global_state", None)
        return state is not None and state.client is not None
    except Exception:
        return False


def init_process_group(coordinator_address=None, num_processes=None,
                       process_id=None, timeout=None, retries=None):
    """Multi-host rendezvous via jax.distributed — replaces the DMLC_PS_ROOT
    scheduler env protocol (SURVEY §3.4). No-op when single-process or when
    the envs are absent.

    Bounded (docs/fault_tolerance.md): the rendezvous waits at most
    `timeout` seconds (default ``MXTPU_RENDEZVOUS_TIMEOUT``, 300) for the
    group to assemble, redialing transient errors `retries` times (default
    ``MXTPU_RENDEZVOUS_RETRIES``, 0) with exponential backoff before
    raising a diagnosable MXNetError — a worker group whose peer died or
    never launched fails fast instead of parking every rank forever (the
    ps-lite scheduler's van timeout analogue, restored for the
    jax.distributed coordinator)."""
    import os
    import time as _time

    import jax

    from ..base import MXNetError

    def _env_int(*names):
        """Protocol-fallback read: first set name wins, MXTPU leg routed
        through the typed registry. A malformed value falls through to the
        next source (registry contract: never crash rendezvous on a typo)."""
        for n in names:
            v = _env.raw(n) if n.startswith("MXTPU_") else os.environ.get(n)
            if v is not None:
                try:
                    return int(v)
                except ValueError:
                    continue
        return None

    # Size/rank resolution order: our protocol, the reference's DMLC
    # protocol, then whatever process manager actually spawned us — OpenMPI
    # (tools/launch.py --launcher mpi), generic PMI, slurm (srun on a TPU
    # pod plays dmlc-tracker's role). The scheduler vars are chosen to only
    # exist on processes the manager really fanned out: OMPI_*/PMI_* appear
    # only under mpirun/mpiexec, and SLURM_STEP_NUM_TASKS is per-srun-step
    # (an sbatch batch script sees SLURM_NTASKS for the *allocation* but its
    # own step is a single task — sniffing SLURM_NTASKS would deadlock a
    # lone `python train.py` inside `sbatch --ntasks=4`).
    if num_processes is None:
        num_processes = _env_int("MXTPU_NUM_WORKERS", "MXNET_TPU_NUM_WORKERS",
                                 "DMLC_NUM_WORKER", "OMPI_COMM_WORLD_SIZE",
                                 "PMI_SIZE", "SLURM_STEP_NUM_TASKS") or 1
    if num_processes <= 1:
        return
    if coordinator_address is None:
        coordinator_address = _env.raw("MXTPU_COORDINATOR")
    if process_id is None:
        process_id = _env_int("MXTPU_PROCESS_ID", "DMLC_WORKER_ID",
                              "OMPI_COMM_WORLD_RANK", "PMI_RANK",
                              "SLURM_PROCID")
    if _group_initialized(jax):
        return  # idempotent re-entry
    if timeout is None:
        # registry default 300; explicit 0 means "fail immediately"
        timeout = _env.get("MXTPU_RENDEZVOUS_TIMEOUT")
    if retries is None:
        # default 0: total time to a clear failure stays within ONE timeout
        # (+ margin) — the acceptance bar for a never-arriving peer. Set
        # MXTPU_RENDEZVOUS_RETRIES>0 for flaky fabrics where a second dial
        # (with backoff) is worth paying the extra timeout windows.
        retries = _env.get("MXTPU_RENDEZVOUS_RETRIES")
    # NOTE: must run before the first jax computation — the backend snapshots
    # the process group at creation (call this before importing anything
    # that touches jax arrays, or at worker start; tools/launch.py pattern)
    _enable_cpu_collectives(jax)

    def _diagnosis(cause):
        return (
            "distributed rendezvous failed (timeout %ds): rank %s of %s "
            "dialing coordinator %s — %s. A peer likely died before "
            "rendezvous or never launched; check the other ranks' logs "
            "(tools/launch.py prefixes them per rank), raise "
            "MXTPU_RENDEZVOUS_TIMEOUT for slow fleets, or use "
            "tools/launch.py --max-restarts for automatic group restart."
            % (timeout, "?" if process_id is None else process_id,
               num_processes, coordinator_address or "<auto-detect>", cause))

    backoff = 1.0
    _recorder.record_event(
        "rendezvous_start", coordinator=coordinator_address or "<auto>",
        num_processes=num_processes, process_id=process_id,
        generation=_telemetry.restart_generation(), timeout_s=timeout)
    t_dial = _time.perf_counter()
    for attempt in range(retries + 1):
        try:
            _dial_with_deadline(jax, coordinator_address, num_processes,
                                process_id, timeout)
            _recorder.record_event(
                "rendezvous_ok",
                seconds=round(_time.perf_counter() - t_dial, 3),
                attempts=attempt + 1)
            _telemetry.counter("mxtpu_rendezvous_total",
                               {"outcome": "ok"}).inc()
            return
        except _RendezvousTimeout:
            # the deadline expired with every side still waiting: the
            # missing peer won't materialize on a redial, so retries are
            # pointless — surface the bounded failure immediately
            _recorder.record_event(
                "rendezvous_failed", cause="deadline",
                seconds=round(_time.perf_counter() - t_dial, 3))
            _telemetry.counter("mxtpu_rendezvous_total",
                               {"outcome": "timeout"}).inc()
            raise MXNetError(_diagnosis(
                "group did not assemble within the deadline")) from None
        except Exception as e:  # bind failure / RuntimeError / grpc error
            # tear down any half-initialized client so a retry starts clean
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            if attempt >= retries:
                _recorder.record_event(
                    "rendezvous_failed", cause=type(e).__name__,
                    seconds=round(_time.perf_counter() - t_dial, 3),
                    attempts=attempt + 1)
                _telemetry.counter("mxtpu_rendezvous_total",
                                   {"outcome": "error"}).inc()
                raise MXNetError(_diagnosis(
                    "%s: %s (after %d attempt(s))"
                    % (type(e).__name__, e, retries + 1))) from e
            _time.sleep(backoff)
            backoff = min(backoff * 2, 30.0)


class _RendezvousTimeout(Exception):
    """Internal: the dial thread outlived the configured deadline."""


def _dial_with_deadline(jax, coordinator_address, num_processes, process_id,
                        timeout):
    """Run jax.distributed.initialize under OUR deadline instead of XLA's.

    XLA's own initialization_timeout is useless as a failure bound: on
    expiry the coordination-service client LOG(FATAL)s — the whole process
    aborts with a C++ stack instead of an exception anything can catch
    (observed: 'Terminating process because the JAX distributed service
    detected fatal errors ... DEADLINE_EXCEEDED ... RegisterTask'). So the
    dial runs on a daemon thread with XLA's deadline pushed far past ours,
    and the calling thread enforces `timeout` with a join: expiry raises a
    catchable _RendezvousTimeout → MXNetError, and the parked dial thread
    dies with the process (the worker exits on the error; even if the
    caller lingers, XLA's far deadline eventually reclaims the thread)."""
    import threading

    box = {}
    lock = threading.Lock()

    def dial():
        try:
            if coordinator_address is None:
                # no launcher-provided coordinator: hand jax the whole
                # rendezvous — its cluster auto-detection covers slurm (srun
                # nodelist), OpenMPI, and Cloud TPU pod metadata, and fails
                # with its own clear error when nothing can resolve. Do NOT
                # pass size/rank: auto-detection derives them from the same
                # source as the coordinator.
                jax.distributed.initialize(
                    initialization_timeout=timeout + 86400)
            else:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                    initialization_timeout=timeout + 86400)
            with lock:
                if box.get("abandoned"):
                    # the caller already reported failure and may have
                    # fallen back to single-process work: a group that
                    # assembles late must NOT silently come alive under it
                    try:
                        jax.distributed.shutdown()
                    except Exception:
                        pass
                else:
                    box["ok"] = True
        except BaseException as e:  # surfaced to the caller below
            box["err"] = e

    t = threading.Thread(target=dial, name="mxtpu-rendezvous-dial",
                         daemon=True)
    t.start()
    t.join(timeout)
    with lock:
        if "ok" in box:
            return
        box["abandoned"] = True
    if "err" in box:
        raise box["err"]
    raise _RendezvousTimeout()


def rank():
    import jax

    return jax.process_index()


def num_workers():
    import jax

    return jax.process_count()


def barrier():
    """Host-level barrier (reference: KVStore::Barrier kvstore.h:364).
    Implemented as a tiny all-device reduction that every participant must
    reach before any can proceed."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from .mesh import default_mesh

    mesh = default_mesh()
    x = jnp.zeros((jax.device_count(),))
    y = jax.device_put(x, NamedSharding(mesh, PartitionSpec(mesh.axis_names[0])))
    jax.block_until_ready(_barrier_sum(y))
