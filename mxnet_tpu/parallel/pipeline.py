"""Pipeline parallelism over the `pp` mesh axis.

Absent from the reference (SURVEY §2.3: "Pipeline parallel — absent; closest
is manual model-parallel layer placement via group2ctx"). TPU-native design:
a GPipe-style microbatch schedule expressed as one `shard_map`-ped
`lax.fori_loop` — each pp device holds ONE stage's parameters; activations
hop to the next stage over `ppermute` (a single ICI neighbor transfer per
tick), so the schedule compiles to a static XLA program with no host
involvement per microbatch.

Constraints (the standard collective-pipeline formulation):
- stages are shape-preserving (activation in == activation out), the
  transformer-layer case pipelining exists for;
- per-stage params are stacked on a leading axis of size `pp` and sharded
  over it (one slice resident per device).

Non-uniform models (embeddings in front, heads behind) are handled by
`PipelineTrainer` (pipeline_trainer.py): prelude/postlude run replicated
outside the loop, only the uniform layer stack is pipelined.

Differentiable end-to-end: `ppermute` has an exact transpose, so
`jax.grad` through `pipeline_apply` yields the backward pipeline schedule
automatically — no hand-written backward pass. Memory control: GPipe's
weakness is storing every microbatch's stage activations for the backward
sweep; `remat=True` wraps the stage in `jax.checkpoint` so only stage
INPUTS are kept and the interior is recomputed during backward — the same
peak-activation bound 1F1B achieves by schedule, achieved functionally
(the XLA scheduler still overlaps the recompute with the ppermute hops).
"""
from __future__ import annotations

import functools

__all__ = ["pipeline_apply", "pipeline_stack_params"]


def pipeline_stack_params(param_list):
    """Stack a list of per-stage pytrees into one pytree with a leading
    stage axis (shard it over `pp` with PartitionSpec('pp', ...))."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *param_list)


def _pipeline_loop(stage_fn, params, xs, axis_name):
    """Runs inside shard_map: params are this device's stage slice
    (leading stage axis of size 1), xs = (x, *extras) — each a full
    (M, ...) microbatch stack. `extras` (e.g. an attention mask) travel
    with their microbatch through the permutes but are not transformed."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    squeeze = jax.tree_util.tree_map(lambda p: p[0], params)
    x = xs[0]
    m = x.shape[0]
    steps = m + n - 1

    state0 = tuple(jnp.zeros_like(a[0]) for a in xs)
    outs0 = jnp.zeros_like(x)

    def body(t, carry):
        state, outs = carry
        # stage 0 consumes microbatch t (while valid); later stages consume
        # what arrived from the left neighbor last tick
        feed = tuple(a[jnp.minimum(t, m - 1)] for a in xs)
        inp = tuple(jnp.where(idx == 0, f, s) for f, s in zip(feed, state))
        out = stage_fn(squeeze, *inp)
        # the last stage finishes microbatch t-(n-1) at tick t
        mb = t - (n - 1)
        valid = (idx == n - 1) & (mb >= 0)
        outs = lax.cond(
            valid,
            lambda o: o.at[jnp.maximum(mb, 0)].set(out),
            lambda o: o,
            outs)
        perm = [(i, (i + 1) % n) for i in range(n)]
        state = tuple(lax.ppermute(a, axis_name, perm)
                      for a in (out,) + inp[1:])
        return state, outs

    _, outs = lax.fori_loop(0, steps, body, (state0, outs0))
    # only the last stage holds real outputs; psum broadcasts them (every
    # other device contributes zeros)
    has = jnp.where(idx == n - 1, 1.0, 0.0)
    return lax.psum(outs * has.astype(outs.dtype), axis_name)


def pipeline_apply(stage_fn, stacked_params, x, num_microbatches=None,
                   axis_name="pp", mesh=None, extras=(), remat=False):
    """Run `stage_fn(params_i, act, *extras) -> act` as a `pp`-deep pipeline.

    stage_fn : callable(stage_params_pytree, activation, *extras) ->
        activation (shape-preserving in the activation).
    stacked_params : pytree with leading stage axis == mesh.shape[axis_name]
        (see pipeline_stack_params).
    x : (B, ...) global batch (replicated over pp; batch dim may be sharded
        over a dp axis of the same mesh); split into `num_microbatches`
        equal microbatches (default: pipeline depth).
    extras : per-sample arrays (B, ...) that accompany each microbatch
        untransformed (attention masks); they ride the same ppermute hops.
    remat : wrap the stage in jax.checkpoint — backward recomputes stage
        interiors instead of storing every microbatch's activations
        (the 1F1B peak-memory bound, achieved functionally).
    Returns (B, ...) outputs, numerically identical to applying the stages
    sequentially.
    """
    import jax

    try:
        from jax import shard_map
    except ImportError:  # older jax: only the experimental location exists
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from .mesh import current_mesh

    mesh = mesh or current_mesh()
    n = mesh.shape[axis_name]
    lead = {leaf.shape[0] for leaf in
            jax.tree_util.tree_leaves(stacked_params)}
    if lead != {n}:
        raise ValueError(
            "stacked_params leading (stage) axis %s must equal the '%s' "
            "mesh axis size %d — shard_map would silently truncate to one "
            "stage per device" % (sorted(lead), axis_name, n))
    b = x.shape[0]
    m = num_microbatches or n
    if b % m:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (b, m))
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def mb_split(a):
        return a.reshape((m, b // m) + a.shape[1:])

    xs = tuple(mb_split(a) for a in (x,) + tuple(extras))

    # microbatch arrays are (M, mb, ...): ride any dp axis on the batch dim
    dp_axes = [ax for ax in ("dp", "fsdp") if ax in mesh.shape
               and mesh.shape[ax] > 1]
    data_spec = P(None, tuple(dp_axes) if dp_axes else None)

    pspec = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    body = functools.partial(_pipeline_loop, fn, axis_name=axis_name)
    try:
        smapped = shard_map(body, mesh=mesh,
                            in_specs=(pspec, tuple(data_spec for _ in xs)),
                            out_specs=data_spec, check_vma=False)
    except TypeError:  # pre-0.9 jax uses check_rep
        smapped = shard_map(body, mesh=mesh,
                            in_specs=(pspec, tuple(data_spec for _ in xs)),
                            out_specs=data_spec, check_rep=False)
    out = smapped(stacked_params, xs)
    return out.reshape((b,) + x.shape[1:])
