"""Pipeline parallelism over the `pp` mesh axis.

Absent from the reference (SURVEY §2.3: "Pipeline parallel — absent; closest
is manual model-parallel layer placement via group2ctx"). TPU-native design:
a GPipe-style microbatch schedule expressed as one `shard_map`-ped
`lax.fori_loop` — each pp device holds ONE stage's parameters; activations
hop to the next stage over `ppermute` (a single ICI neighbor transfer per
tick), so the schedule compiles to a static XLA program with no host
involvement per microbatch.

Constraints (the standard collective-pipeline formulation):
- stages are shape-preserving (activation in == activation out), the
  transformer-layer case pipelining exists for;
- per-stage params are stacked on a leading axis of size `pp` and sharded
  over it (one slice resident per device).

Differentiable end-to-end: `ppermute` has an exact transpose, so
`jax.grad` through `pipeline_apply` yields the 1F1B-equivalent backward
schedule automatically — no hand-written backward pass.
"""
from __future__ import annotations

import functools

__all__ = ["pipeline_apply", "pipeline_stack_params"]


def pipeline_stack_params(param_list):
    """Stack a list of per-stage pytrees into one pytree with a leading
    stage axis (shard it over `pp` with PartitionSpec('pp', ...))."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *param_list)


def _pipeline_loop(stage_fn, params, x, axis_name):
    """Runs inside shard_map: params are this device's stage slice
    (leading stage axis of size 1), x is the full (M, ...) microbatch
    stack (replicated)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    squeeze = jax.tree_util.tree_map(lambda p: p[0], params)
    m = x.shape[0]
    steps = m + n - 1

    state0 = jnp.zeros_like(x[0])
    outs0 = jnp.zeros_like(x)

    def body(t, carry):
        state, outs = carry
        # stage 0 consumes microbatch t (while valid); later stages consume
        # what arrived from the left neighbor last tick
        feed = x[jnp.minimum(t, m - 1)]
        inp = jnp.where(idx == 0, feed, state)
        out = stage_fn(squeeze, inp)
        # the last stage finishes microbatch t-(n-1) at tick t
        mb = t - (n - 1)
        valid = (idx == n - 1) & (mb >= 0)
        outs = lax.cond(
            valid,
            lambda o: o.at[jnp.maximum(mb, 0)].set(out),
            lambda o: o,
            outs)
        state = lax.ppermute(out, axis_name,
                             [(i, (i + 1) % n) for i in range(n)])
        return state, outs

    _, outs = lax.fori_loop(0, steps, body, (state0, outs0))
    # only the last stage holds real outputs; psum broadcasts them (every
    # other device contributes zeros)
    has = jnp.where(idx == n - 1, 1.0, 0.0)
    return lax.psum(outs * has.astype(outs.dtype), axis_name)


def pipeline_apply(stage_fn, stacked_params, x, num_microbatches=None,
                   axis_name="pp", mesh=None):
    """Run `stage_fn(params_i, act) -> act` as a `pp`-deep pipeline.

    stage_fn : callable(stage_params_pytree, activation) -> activation
        (shape-preserving).
    stacked_params : pytree with leading stage axis == mesh.shape[axis_name]
        (see pipeline_stack_params).
    x : (B, ...) global batch (replicated); split into `num_microbatches`
        equal microbatches (default: pipeline depth).
    Returns (B, ...) outputs, numerically identical to applying the stages
    sequentially.
    """
    import jax
    import jax.numpy as jnp

    try:
        from jax import shard_map
    except ImportError:  # older jax: only the experimental location exists
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from .mesh import current_mesh

    mesh = mesh or current_mesh()
    n = mesh.shape[axis_name]
    lead = {leaf.shape[0] for leaf in
            jax.tree_util.tree_leaves(stacked_params)}
    if lead != {n}:
        raise ValueError(
            "stacked_params leading (stage) axis %s must equal the '%s' "
            "mesh axis size %d — shard_map would silently truncate to one "
            "stage per device" % (sorted(lead), axis_name, n))
    b = x.shape[0]
    m = num_microbatches or n
    if b % m:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (b, m))
    xm = x.reshape((m, b // m) + x.shape[1:])

    pspec = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    body = functools.partial(_pipeline_loop, stage_fn, axis_name=axis_name)
    try:
        fn = shard_map(body, mesh=mesh, in_specs=(pspec, P()),
                       out_specs=P(), check_vma=False)
    except TypeError:  # pre-0.9 jax uses check_rep
        fn = shard_map(body, mesh=mesh, in_specs=(pspec, P()),
                       out_specs=P(), check_rep=False)
    out = fn(stacked_params, xm)
    return out.reshape((b,) + x.shape[1:])
