"""Library/include path discovery (reference: python/mxnet/libinfo.py —
find_lib_path locates libmxnet.so for ctypes consumers, find_include_path
the C headers). Here the native artifacts are the lazily-built runtime
libraries (lib/native.py) and the flat C predict ABI header."""
from __future__ import annotations

import os

__all__ = ["find_lib_path", "find_include_path", "__version__"]

__version__ = "2.0.0.tpu"

_LIB_DIR = os.path.join(os.path.dirname(__file__), "lib")


def find_lib_path():
    """Paths of the native shared objects, built on demand (reference:
    libinfo.py find_lib_path — raises if no library can be found)."""
    from .lib import native

    paths = []
    if native.get() is not None:
        paths.append(os.path.join(_LIB_DIR, "libmxtpu.so"))
    if native.get_capi() is not None:
        paths.append(os.path.join(_LIB_DIR, "libmxtpu_capi.so"))
    if not paths:
        raise RuntimeError(
            "Cannot build/find the native libraries (g++ unavailable?). "
            "The pure-Python paths still work; the C predict ABI does not.")
    return paths


def find_include_path():
    """Directory of the C API headers (reference: libinfo.py
    find_include_path)."""
    inc = os.path.join(_LIB_DIR, "include")
    if not os.path.isdir(inc):
        raise RuntimeError("include directory missing: %s" % inc)
    return inc
