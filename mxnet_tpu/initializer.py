"""Weight initializers (reference: python/mxnet/initializer.py, 752 LoC).

Same registry + name-pattern dispatch as the reference: an Initializer is
called with (InitDesc(name, attrs), NDArray) and fills the array based on the
parameter's name suffix (weight/bias/gamma/beta/...)."""
from __future__ import annotations

import numpy as _np

from .base import _Registry
from . import ndarray as nd
from . import random as _random

__all__ = ["InitDesc", "Initializer", "Uniform", "Normal", "Zero", "One",
           "Constant", "Xavier", "MSRAPrelu", "Orthogonal", "Bilinear",
           "LSTMBias", "FusedRNN", "Load", "Mixed", "register", "create"]

_REG = _Registry("initializer")


def register(klass):
    _REG.register(klass, klass.__name__)
    return klass


def create(init, **kwargs):
    if init is None:
        return Uniform()
    if isinstance(init, Initializer):
        return init
    if isinstance(init, str):
        if init.startswith("["):
            # dumps() form: json [name, kwargs] (reference initializer.py
            # round-trips symbol __init__ attrs this way)
            import json

            name, kw = json.loads(init)
            return _REG.create(name, **kw)
        return _REG.create(init, **kwargs)
    raise TypeError("cannot create initializer from %r" % (init,))


class InitDesc(str):
    """Parameter name + attrs hint (reference: initializer.py:38)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base initializer (reference: initializer.py:92)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        """json [name, kwargs] string form, stored in symbol `__init__`
        attrs and round-tripped by create() (reference: initializer.py
        dumps)."""
        import json

        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(desc)
        init_hint = desc.attrs.get("__init__", "")
        if init_hint:
            create(init_hint)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(desc, arr)
        elif name.endswith("min") or name.endswith("max"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # hooks
    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_bias(self, desc, arr):
        arr[:] = 0.0

    def _init_gamma(self, desc, arr):
        arr[:] = 1.0

    def _init_beta(self, desc, arr):
        arr[:] = 0.0

    def _init_zero(self, desc, arr):
        arr[:] = 0.0

    def _init_one(self, desc, arr):
        arr[:] = 1.0

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)

    def _rand(self, shape):
        return _random.np_random().random(shape)

    def __repr__(self):
        return "%s(%s)" % (type(self).__name__, self._kwargs)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        arr[:] = _np.random.uniform(-self.scale, self.scale, arr.shape).astype(_np.float32)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        arr[:] = _np.random.normal(0, self.sigma, arr.shape).astype(_np.float32)


@register
class Zero(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 0.0


register(Zero)
_REG.register(Zero, "zeros")


@register
class One(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 1.0


_REG.register(One, "ones")


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, arr):
        arr[:] = self.value


@register
class Xavier(Initializer):
    """reference: initializer.py Xavier — gaussian/uniform scaled by fan avg/in/out."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError("Xavier requires ndim >= 2, got %s for %s" % (shape, desc))
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        else:
            factor = fan_out
        scale = _np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = _np.random.uniform(-scale, scale, shape).astype(_np.float32)
        else:
            arr[:] = _np.random.normal(0, scale, shape).astype(_np.float32)


@register
class MSRAPrelu(Xavier):
    """reference: initializer.py MSRAPrelu (He init)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = _np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape).astype(_np.float32)


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (reference: initializer.py Bilinear)."""

    def _init_weight(self, desc, arr):
        weight = _np.zeros(arr.shape, dtype=_np.float32)
        shape = arr.shape
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(_np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight


@register
class LSTMBias(Initializer):
    """Forget-gate bias = forget_bias, rest 0 (reference: initializer.py)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = _np.zeros(arr.shape, dtype=_np.float32)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = b

    _init_bias = _init_weight
    _init_default = _init_weight


@register
class Load(Initializer):
    """Init from a dict of arrays with fallback (reference: initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        super().__init__()
        self.param = {k.replace("arg:", "").replace("aux:", ""): v
                      for k, v in param.items()}
        self.default_init = default_init

    def __call__(self, desc, arr):
        name = str(desc)
        if name in self.param:
            src = self.param[name]
            arr[:] = src.asnumpy() if hasattr(src, "asnumpy") else src
        elif self.default_init is not None:
            self.default_init(desc, arr)
        else:
            raise ValueError("no init value for %s" % name)


@register
class Mixed(Initializer):
    """Pattern-matched initializer list (reference: initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        import re

        super().__init__()
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, desc, arr):
        for prog, init in self.map:
            if prog.match(str(desc)):
                init(desc, arr)
                return
        raise ValueError("no matching initializer pattern for %s" % str(desc))


@register
class FusedRNN(Initializer):
    """Initialize a FusedRNNCell's flat parameter vector (reference:
    initializer.py:702): unpack into per-gate matrices, apply `init` (or
    the global initializer) to each, force the lstm forget-gate bias,
    repack."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        import json

        if isinstance(init, str):
            name, kw = json.loads(init)
            init = _REG.create(name, **kw)
        super().__init__(init=init.dumps() if init is not None else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from . import ndarray as nd
        from .rnn import rnn_cell

        cell = rnn_cell.FusedRNNCell(
            self._num_hidden, self._num_layers, self._mode,
            self._bidirectional, forget_bias=self._forget_bias, prefix="")
        args = cell.unpack_weights(
            {"parameters": nd.array(_np.asarray(arr, dtype=_np.float32))})
        for name in args:
            sub = _np.array(args[name].asnumpy(), copy=True)
            if self._mode == "lstm" and name.endswith("_f_bias"):
                sub[:] = self._forget_bias
            else:
                inner = self._init if self._init is not None else \
                    (desc.global_init if getattr(desc, "global_init", None)
                     else Uniform())
                inner(InitDesc(name, global_init=getattr(
                    desc, "global_init", None)), sub)
            args[name] = nd.array(sub)
        arr[:] = cell.pack_weights(args)["parameters"].asnumpy()


# convenience namespace mirroring mx.init.*
class init:
    Uniform = Uniform
    Normal = Normal
    Zero = Zero
    One = One
    Constant = Constant
    Xavier = Xavier
    MSRAPrelu = MSRAPrelu
    Orthogonal = Orthogonal
    Bilinear = Bilinear
    LSTMBias = LSTMBias
    FusedRNN = FusedRNN
    Load = Load
    Mixed = Mixed
    Initializer = Initializer
    InitDesc = InitDesc
