"""Loader for the native C++ runtime library (libmxtpu).

Compiles `mxnet_tpu/lib/src/*.cc` into a shared object with g++ on first use
(cached next to the sources; rebuilt when any source is newer) and exposes it
through ctypes. The reference ships its runtime as a prebuilt libmxnet.so
behind a C ABI (include/mxnet/c_api.h); here the surface is the small host
runtime that stays native in a TPU build: RecordIO, the threaded data
pipeline, and host staging buffers.
"""
from __future__ import annotations

import ctypes
import glob
import os
import subprocess
import threading

_LIB_DIR = os.path.dirname(__file__)


class _Loader:
    """Build-once/load-once holder for one native shared object: mtime-based
    rebuild cache, g++ subprocess (failures degrade to None so pure-Python
    fallbacks kick in), MXTPU_NO_NATIVE gate, double-checked-lock load."""

    def __init__(self, src_subdir, so_name, extra_flags=(), cdll_mode=None):
        self._src_dir = os.path.join(_LIB_DIR, src_subdir)
        self._so_path = os.path.join(_LIB_DIR, so_name)
        self._extra_flags = extra_flags
        self._cdll_mode = cdll_mode
        self._lock = threading.Lock()
        self._lib = None
        self._tried = False

    def _build(self):
        sources = sorted(glob.glob(os.path.join(self._src_dir, "*.cc")))
        if not sources:
            return None
        deps = sources + glob.glob(os.path.join(self._src_dir, "*.h"))
        if os.path.exists(self._so_path):
            so_mtime = os.path.getmtime(self._so_path)
            if all(os.path.getmtime(s) <= so_mtime for s in deps):
                return self._so_path
        flags = []
        for f in self._extra_flags:
            flags.extend(f() if callable(f) else [f])
        pre = [f for f in flags if f.startswith("-I")]
        post = [f for f in flags if not f.startswith("-I")]
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread"] \
            + pre + ["-o", self._so_path] + sources + post
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            return None
        return self._so_path

    def get(self):
        if self._lib is not None or self._tried:
            return self._lib
        with self._lock:
            if self._lib is None and not self._tried:
                self._tried = True
                from .. import env as _env

                if _env.get("MXTPU_NO_NATIVE"):
                    return None
                path = self._build()
                if path is not None:
                    try:
                        if self._cdll_mode is None:
                            self._lib = ctypes.CDLL(path)
                        else:
                            self._lib = ctypes.CDLL(path,
                                                    mode=self._cdll_mode)
                    except OSError:
                        self._lib = None
        return self._lib


def _python_link_flags():
    """-I/-L/-l flags for embedding CPython (the capi lib only)."""
    import sysconfig

    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = (sysconfig.get_config_var("LDVERSION")
           or sysconfig.get_config_var("VERSION") or "3")
    return ["-I" + inc, "-L" + libdir, "-lpython" + ver,
            "-Wl,-rpath," + libdir]


_MAIN = _Loader("src", "libmxtpu.so")
# separate lib: only this one embeds/links CPython. RTLD_GLOBAL so it
# resolves libpython symbols from the hosting interpreter under ctypes.
_CAPI = _Loader("src_capi", "libmxtpu_capi.so",
                extra_flags=(lambda: _python_link_flags(),),
                cdll_mode=ctypes.RTLD_GLOBAL)


def get():
    """The loaded runtime CDLL (libmxtpu.so), or None if unavailable."""
    return _MAIN.get()


def get_capi():
    """The loaded C predict-API CDLL (libmxtpu_capi.so), or None."""
    return _CAPI.get()


def available():
    return get() is not None


def _checked(lib):
    """Declare argtypes/restypes once per load."""
    if getattr(lib, "_mxtpu_typed", False):
        return lib
    c = ctypes
    lib.mxtpu_recio_reader_open.argtypes = [c.c_char_p]
    lib.mxtpu_recio_reader_open.restype = c.c_void_p
    lib.mxtpu_recio_reader_next.argtypes = [c.c_void_p,
                                            c.POINTER(c.POINTER(c.c_char)),
                                            c.POINTER(c.c_uint64)]
    lib.mxtpu_recio_reader_next.restype = c.c_int
    lib.mxtpu_recio_reader_read_at.argtypes = [c.c_void_p, c.c_uint64,
                                               c.POINTER(c.POINTER(c.c_char)),
                                               c.POINTER(c.c_uint64)]
    lib.mxtpu_recio_reader_read_at.restype = c.c_int
    lib.mxtpu_recio_reader_tell.argtypes = [c.c_void_p]
    lib.mxtpu_recio_reader_tell.restype = c.c_int64
    lib.mxtpu_recio_reader_reset.argtypes = [c.c_void_p]
    lib.mxtpu_recio_reader_close.argtypes = [c.c_void_p]
    lib.mxtpu_recio_writer_open.argtypes = [c.c_char_p]
    lib.mxtpu_recio_writer_open.restype = c.c_void_p
    lib.mxtpu_recio_writer_tell.argtypes = [c.c_void_p]
    lib.mxtpu_recio_writer_tell.restype = c.c_int64
    lib.mxtpu_recio_writer_write.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.mxtpu_recio_writer_write.restype = c.c_int
    lib.mxtpu_recio_writer_close.argtypes = [c.c_void_p]
    lib.mxtpu_prefetch_open.argtypes = [c.c_char_p, c.c_uint64]
    lib.mxtpu_prefetch_open.restype = c.c_void_p
    lib.mxtpu_prefetch_next.argtypes = [c.c_void_p,
                                        c.POINTER(c.POINTER(c.c_char)),
                                        c.POINTER(c.c_uint64)]
    lib.mxtpu_prefetch_next.restype = c.c_int
    lib.mxtpu_prefetch_close.argtypes = [c.c_void_p]
    lib.mxtpu_pool_alloc.argtypes = [c.c_size_t]
    lib.mxtpu_pool_alloc.restype = c.c_void_p
    lib.mxtpu_pool_free.argtypes = [c.c_void_p]
    lib.mxtpu_pool_trim.argtypes = []
    lib.mxtpu_pool_stats.argtypes = [c.POINTER(c.c_uint64)] * 4
    lib._mxtpu_typed = True
    return lib


class RecordReader:
    """Sequential/random-access native record reader."""

    def __init__(self, path):
        self._lib = _checked(get())
        self._h = self._lib.mxtpu_recio_reader_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s" % path)

    def read(self):
        buf = ctypes.POINTER(ctypes.c_char)()
        ln = ctypes.c_uint64()
        st = self._lib.mxtpu_recio_reader_next(self._h, ctypes.byref(buf),
                                               ctypes.byref(ln))
        if st == 0:
            return None
        if st < 0:
            raise IOError("corrupt recordio stream")
        return ctypes.string_at(buf, ln.value)

    def read_at(self, pos):
        buf = ctypes.POINTER(ctypes.c_char)()
        ln = ctypes.c_uint64()
        st = self._lib.mxtpu_recio_reader_read_at(self._h, pos,
                                                  ctypes.byref(buf),
                                                  ctypes.byref(ln))
        if st < 0:
            raise IOError("corrupt recordio stream / bad offset %d" % pos)
        if st == 0:
            return None
        return ctypes.string_at(buf, ln.value)

    def tell(self):
        return self._lib.mxtpu_recio_reader_tell(self._h)

    def reset(self):
        self._lib.mxtpu_recio_reader_reset(self._h)

    def close(self):
        if self._h:
            self._lib.mxtpu_recio_reader_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class RecordWriter:
    def __init__(self, path):
        self._lib = _checked(get())
        self._h = self._lib.mxtpu_recio_writer_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s for writing" % path)

    def tell(self):
        return self._lib.mxtpu_recio_writer_tell(self._h)

    def write(self, buf):
        if self._lib.mxtpu_recio_writer_write(self._h, buf, len(buf)) != 0:
            raise IOError("recordio write failed")

    def close(self):
        if self._h:
            self._lib.mxtpu_recio_writer_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PrefetchReader:
    """Background-thread record reader (bounded queue in C++)."""

    def __init__(self, path, capacity=16):
        self._lib = _checked(get())
        self._h = self._lib.mxtpu_prefetch_open(path.encode(), capacity)
        if not self._h:
            raise IOError("cannot open %s" % path)

    def read(self):
        buf = ctypes.POINTER(ctypes.c_char)()
        ln = ctypes.c_uint64()
        st = self._lib.mxtpu_prefetch_next(self._h, ctypes.byref(buf),
                                           ctypes.byref(ln))
        if st == 0:
            return None
        if st < 0:
            raise IOError("corrupt recordio stream")
        return ctypes.string_at(buf, ln.value)

    def close(self):
        if self._h:
            self._lib.mxtpu_prefetch_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def pool_stats():
    lib = _checked(get())
    vals = [ctypes.c_uint64() for _ in range(4)]
    lib.mxtpu_pool_stats(*[ctypes.byref(v) for v in vals])
    return {"bytes_allocated": vals[0].value, "bytes_live": vals[1].value,
            "hits": vals[2].value, "misses": vals[3].value}
