"""Loader for the native C++ runtime library (libmxtpu).

Compiles `mxnet_tpu/lib/src/*.cc` into a shared object with g++ on first use
(cached next to the sources; rebuilt when any source is newer) and exposes it
through ctypes. The reference ships its runtime as a prebuilt libmxnet.so
behind a C ABI (include/mxnet/c_api.h); here the surface is the small host
runtime that stays native in a TPU build: RecordIO, the threaded data
pipeline, and host staging buffers.
"""
from __future__ import annotations

import ctypes
import glob
import os
import subprocess
import threading

_LOCK = threading.Lock()
_LIB = None
_TRIED = False

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_SO_PATH = os.path.join(os.path.dirname(__file__), "libmxtpu.so")


def _build():
    sources = sorted(glob.glob(os.path.join(_SRC_DIR, "*.cc")))
    if not sources:
        return None
    if os.path.exists(_SO_PATH):
        so_mtime = os.path.getmtime(_SO_PATH)
        if all(os.path.getmtime(s) <= so_mtime for s in sources):
            return _SO_PATH
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", _SO_PATH] + sources
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return None
    return _SO_PATH


def get():
    """The loaded CDLL, or None if unavailable."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is None and not _TRIED:
            _TRIED = True
            if os.environ.get("MXTPU_NO_NATIVE"):
                return None
            path = _build()
            if path is not None:
                try:
                    _LIB = ctypes.CDLL(path)
                except OSError:
                    _LIB = None
    return _LIB


def available():
    return get() is not None
