"""Loader for the native C++ runtime library (libmxtpu).

Compiles `mxnet_tpu/lib/src/*.cc` into a shared object with g++ on first use
(cached next to the sources; rebuilt when any source is newer) and exposes it
through ctypes. The reference ships its runtime as a prebuilt libmxnet.so
behind a C ABI (include/mxnet/c_api.h); here the surface is the small host
runtime that stays native in a TPU build: RecordIO, the threaded data
pipeline, and host staging buffers.
"""
from __future__ import annotations

import ctypes
import glob
import os
import subprocess
import threading

_LOCK = threading.Lock()
_LIB = None
_TRIED = False

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_SO_PATH = os.path.join(os.path.dirname(__file__), "libmxtpu.so")


def _build():
    sources = sorted(glob.glob(os.path.join(_SRC_DIR, "*.cc")))
    if not sources:
        return None
    if os.path.exists(_SO_PATH):
        so_mtime = os.path.getmtime(_SO_PATH)
        if all(os.path.getmtime(s) <= so_mtime for s in sources):
            return _SO_PATH
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", _SO_PATH] + sources
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return None
    return _SO_PATH


def get():
    """The loaded CDLL, or None if unavailable."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is None and not _TRIED:
            _TRIED = True
            if os.environ.get("MXTPU_NO_NATIVE"):
                return None
            path = _build()
            if path is not None:
                try:
                    _LIB = ctypes.CDLL(path)
                except OSError:
                    _LIB = None
    return _LIB


def available():
    return get() is not None


def _checked(lib):
    """Declare argtypes/restypes once per load."""
    if getattr(lib, "_mxtpu_typed", False):
        return lib
    c = ctypes
    lib.mxtpu_recio_reader_open.argtypes = [c.c_char_p]
    lib.mxtpu_recio_reader_open.restype = c.c_void_p
    lib.mxtpu_recio_reader_next.argtypes = [c.c_void_p,
                                            c.POINTER(c.POINTER(c.c_char)),
                                            c.POINTER(c.c_uint64)]
    lib.mxtpu_recio_reader_next.restype = c.c_int
    lib.mxtpu_recio_reader_read_at.argtypes = [c.c_void_p, c.c_uint64,
                                               c.POINTER(c.POINTER(c.c_char)),
                                               c.POINTER(c.c_uint64)]
    lib.mxtpu_recio_reader_read_at.restype = c.c_int
    lib.mxtpu_recio_reader_tell.argtypes = [c.c_void_p]
    lib.mxtpu_recio_reader_tell.restype = c.c_int64
    lib.mxtpu_recio_reader_reset.argtypes = [c.c_void_p]
    lib.mxtpu_recio_reader_close.argtypes = [c.c_void_p]
    lib.mxtpu_recio_writer_open.argtypes = [c.c_char_p]
    lib.mxtpu_recio_writer_open.restype = c.c_void_p
    lib.mxtpu_recio_writer_tell.argtypes = [c.c_void_p]
    lib.mxtpu_recio_writer_tell.restype = c.c_int64
    lib.mxtpu_recio_writer_write.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.mxtpu_recio_writer_write.restype = c.c_int
    lib.mxtpu_recio_writer_close.argtypes = [c.c_void_p]
    lib.mxtpu_prefetch_open.argtypes = [c.c_char_p, c.c_uint64]
    lib.mxtpu_prefetch_open.restype = c.c_void_p
    lib.mxtpu_prefetch_next.argtypes = [c.c_void_p,
                                        c.POINTER(c.POINTER(c.c_char)),
                                        c.POINTER(c.c_uint64)]
    lib.mxtpu_prefetch_next.restype = c.c_int
    lib.mxtpu_prefetch_close.argtypes = [c.c_void_p]
    lib.mxtpu_pool_alloc.argtypes = [c.c_size_t]
    lib.mxtpu_pool_alloc.restype = c.c_void_p
    lib.mxtpu_pool_free.argtypes = [c.c_void_p]
    lib.mxtpu_pool_trim.argtypes = []
    lib.mxtpu_pool_stats.argtypes = [c.POINTER(c.c_uint64)] * 4
    lib._mxtpu_typed = True
    return lib


class RecordReader:
    """Sequential/random-access native record reader."""

    def __init__(self, path):
        self._lib = _checked(get())
        self._h = self._lib.mxtpu_recio_reader_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s" % path)

    def read(self):
        buf = ctypes.POINTER(ctypes.c_char)()
        ln = ctypes.c_uint64()
        st = self._lib.mxtpu_recio_reader_next(self._h, ctypes.byref(buf),
                                               ctypes.byref(ln))
        if st == 0:
            return None
        if st < 0:
            raise IOError("corrupt recordio stream")
        return ctypes.string_at(buf, ln.value)

    def read_at(self, pos):
        buf = ctypes.POINTER(ctypes.c_char)()
        ln = ctypes.c_uint64()
        st = self._lib.mxtpu_recio_reader_read_at(self._h, pos,
                                                  ctypes.byref(buf),
                                                  ctypes.byref(ln))
        if st < 0:
            raise IOError("corrupt recordio stream / bad offset %d" % pos)
        if st == 0:
            return None
        return ctypes.string_at(buf, ln.value)

    def tell(self):
        return self._lib.mxtpu_recio_reader_tell(self._h)

    def reset(self):
        self._lib.mxtpu_recio_reader_reset(self._h)

    def close(self):
        if self._h:
            self._lib.mxtpu_recio_reader_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class RecordWriter:
    def __init__(self, path):
        self._lib = _checked(get())
        self._h = self._lib.mxtpu_recio_writer_open(path.encode())
        if not self._h:
            raise IOError("cannot open %s for writing" % path)

    def tell(self):
        return self._lib.mxtpu_recio_writer_tell(self._h)

    def write(self, buf):
        if self._lib.mxtpu_recio_writer_write(self._h, buf, len(buf)) != 0:
            raise IOError("recordio write failed")

    def close(self):
        if self._h:
            self._lib.mxtpu_recio_writer_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class PrefetchReader:
    """Background-thread record reader (bounded queue in C++)."""

    def __init__(self, path, capacity=16):
        self._lib = _checked(get())
        self._h = self._lib.mxtpu_prefetch_open(path.encode(), capacity)
        if not self._h:
            raise IOError("cannot open %s" % path)

    def read(self):
        buf = ctypes.POINTER(ctypes.c_char)()
        ln = ctypes.c_uint64()
        st = self._lib.mxtpu_prefetch_next(self._h, ctypes.byref(buf),
                                           ctypes.byref(ln))
        if st == 0:
            return None
        if st < 0:
            raise IOError("corrupt recordio stream")
        return ctypes.string_at(buf, ln.value)

    def close(self):
        if self._h:
            self._lib.mxtpu_prefetch_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def pool_stats():
    lib = _checked(get())
    vals = [ctypes.c_uint64() for _ in range(4)]
    lib.mxtpu_pool_stats(*[ctypes.byref(v) for v in vals])
    return {"bytes_allocated": vals[0].value, "bytes_live": vals[1].value,
            "hits": vals[2].value, "misses": vals[3].value}
