// Minimal imperative flat C ABI — the NDArray/invoke/autograd core of the
// reference's include/mxnet/c_api.h (213 entry points; this implements the
// ~16 that make non-Python bindings possible, mirroring
// src/c_api/c_api_ndarray.cc MXImperativeInvoke :132 and the autograd
// control surface :257-281). Signatures follow the reference so a C host
// written against libmxnet's NDArray core recompiles unchanged.
//
// Handle model: every NDArrayHandle owns a strong reference to a Python
// `mxnet_tpu.ndarray.NDArray`; ops are invoked by name through
// mxnet_tpu/capi_bridge.py (the reference invokes via AtomicSymbolCreator
// handles obtained from MXSymbolListAtomicSymbolCreators — here a creator
// handle IS an interned op-name string, which
// MXSymbolGetAtomicSymbolName reports, so the reference's
// creator-discovery flow works verbatim).
//
// Build: compiled into libmxtpu_capi.so together with c_predict_api.cc
// (see mxnet_tpu/lib/native.py get_capi()).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

#include "capi_common.h"

typedef void *NDArrayHandle;
typedef void *AtomicSymbolCreator;

namespace {

using mxtpu_capi::GIL;
using mxtpu_capi::g_last_error;
using mxtpu_capi::set_error_from_python;

PyObject *call_bridge(const char *fn, PyObject *args) {
  return mxtpu_capi::call_module_fn("mxnet_tpu.capi_bridge", fn, args);
}

// call_bridge with a single-object argument, owning the argument tuple
// (call_module_fn does NOT consume its args — without this the "(O)"
// tuples leak a strong NDArray reference per call)
PyObject *call_bridge1(const char *fn, PyObject *obj) {
  PyObject *args = Py_BuildValue("(O)", obj);
  if (args == nullptr) return nullptr;
  PyObject *res = mxtpu_capi::call_module_fn("mxnet_tpu.capi_bridge", fn,
                                             args);
  Py_DECREF(args);
  return res;
}

using mxtpu_capi::ND;  // shared handle layout (capi_common.h)

ND *nd(NDArrayHandle h) { return static_cast<ND *>(h); }

// process-lifetime storage backing creator handles and ListAllOpNames
std::vector<std::string> *g_op_names = nullptr;
std::vector<const char *> *g_op_cstrs = nullptr;

int ensure_op_names() {
  // all checks under the GIL: a lock-free fast path would race the
  // publication of g_op_cstrs (these calls are rare; the GIL is cheap)
  GIL gil;
  if (g_op_names != nullptr) return 0;
  PyObject *res = call_bridge("_capi_list_ops", nullptr);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  auto *names = new std::vector<std::string>();
  Py_ssize_t n = PyList_Size(res);
  for (Py_ssize_t i = 0; i < n; ++i)
    names->push_back(PyUnicode_AsUTF8(PyList_GetItem(res, i)));
  Py_DECREF(res);
  auto *cstrs = new std::vector<const char *>();
  for (const std::string &s : *names) cstrs->push_back(s.c_str());
  g_op_cstrs = cstrs;
  g_op_names = names;   // publish last
  return 0;
}

}  // namespace

extern "C" {

int MXGetVersion(int *out) {
  GIL gil;
  PyObject *res = call_bridge("_capi_version", nullptr);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle *out) {
  (void)delay_alloc;  // XLA buffers allocate lazily anyway
  *out = nullptr;
  GIL gil;
  PyObject *shp = PyTuple_New(ndim);
  if (shp == nullptr) {
    set_error_from_python();
    return -1;
  }
  for (mx_uint i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject *args = Py_BuildValue("(Oiii)", shp, dev_type, dev_id, dtype);
  Py_DECREF(shp);
  PyObject *res = args ? call_bridge("_capi_nd_create", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  ND *h = new ND();
  h->obj = res;
  *out = h;
  return 0;
}

int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle *out) {
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc,
                           /*dtype=*/0, out);
}

int MXNDArrayFree(NDArrayHandle handle) {
  ND *h = nd(handle);
  if (h == nullptr) return 0;
  {
    GIL gil;
    Py_DECREF(h->obj);
  }
  delete h;
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size) {
  // reference semantics (c_api.cc): `size` counts ELEMENTS, not bytes;
  // the byte width comes from the array's dtype (authoritative in
  // capi_bridge._capi_nd_itemsize — no table duplicated here)
  ND *h = nd(handle);
  GIL gil;
  PyObject *it = call_bridge1("_capi_nd_itemsize", h->obj);
  if (it == nullptr) {
    set_error_from_python();
    return -1;
  }
  size_t width = PyLong_AsSize_t(it);
  Py_DECREF(it);
  PyObject *args = Py_BuildValue("(Oy#)", h->obj,
                                 static_cast<const char *>(data),
                                 static_cast<Py_ssize_t>(size * width));
  PyObject *res = args ? call_bridge("_capi_nd_sync_copy_from", args)
                       : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t size) {
  ND *h = nd(handle);
  GIL gil;
  PyObject *res = call_bridge1("_capi_nd_sync_copy_to", h->obj);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    Py_DECREF(res);
    set_error_from_python();
    return -1;
  }
  size_t total = static_cast<size_t>(len);
  // `size` counts elements and must match the array exactly — the
  // reference CHECKs the size instead of silently truncating (a smaller
  // `size` would hide bugs; size==0 on a non-empty array would overflow
  // the caller's buffer if treated as "copy all")
  PyObject *it = call_bridge1("_capi_nd_itemsize", h->obj);
  if (it == nullptr) {
    Py_DECREF(res);
    set_error_from_python();
    return -1;
  }
  size_t width = PyLong_AsSize_t(it);
  Py_DECREF(it);
  if (width == 0 || size * width != total) {
    Py_DECREF(res);
    g_last_error = "MXNDArraySyncCopyToCPU: size (elements) does not "
                   "match the array";
    return -1;
  }
  std::memcpy(data, buf, total);
  Py_DECREF(res);
  return 0;
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                      const mx_uint **out_pdata) {
  ND *h = nd(handle);
  GIL gil;
  PyObject *res = call_bridge1("_capi_nd_shape", h->obj);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  h->shape.clear();
  for (Py_ssize_t i = 0; i < PyTuple_Size(res); ++i)
    h->shape.push_back(
        static_cast<mx_uint>(PyLong_AsUnsignedLong(PyTuple_GetItem(res, i))));
  Py_DECREF(res);
  *out_dim = static_cast<mx_uint>(h->shape.size());
  *out_pdata = h->shape.data();
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype) {
  ND *h = nd(handle);
  GIL gil;
  PyObject *res = call_bridge1("_capi_nd_dtype", h->obj);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out_dtype = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                        int *out_dev_id) {
  ND *h = nd(handle);
  GIL gil;
  PyObject *res = call_bridge1("_capi_nd_context", h->obj);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out_dev_type = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(res, 0)));
  *out_dev_id = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(res, 1)));
  Py_DECREF(res);
  return 0;
}

int MXListAllOpNames(mx_uint *out_size, const char ***out_array) {
  if (ensure_op_names() != 0) return -1;
  *out_size = static_cast<mx_uint>(g_op_cstrs->size());
  *out_array = g_op_cstrs->data();
  return 0;
}

int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                     AtomicSymbolCreator **out_array) {
  // creator handle == interned op-name string (stable for process life)
  if (ensure_op_names() != 0) return -1;
  *out_size = static_cast<mx_uint>(g_op_cstrs->size());
  *out_array = reinterpret_cast<AtomicSymbolCreator *>(
      const_cast<char **>(g_op_cstrs->data()));
  return 0;
}

int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char **name) {
  *name = static_cast<const char *>(creator);
  return 0;
}

int MXImperativeInvoke(AtomicSymbolCreator creator, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, int num_params,
                       const char **param_keys, const char **param_vals) {
  const char *op_name = static_cast<const char *>(creator);
  GIL gil;
  PyObject *ins = PyList_New(num_inputs);
  PyObject *keys = PyList_New(num_params);
  PyObject *vals = PyList_New(num_params);
  if (ins == nullptr || keys == nullptr || vals == nullptr) {
    Py_XDECREF(ins);
    Py_XDECREF(keys);
    Py_XDECREF(vals);
    set_error_from_python();
    return -1;
  }
  for (int i = 0; i < num_inputs; ++i) {
    PyObject *o = nd(inputs[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(ins, i, o);
  }
  for (int i = 0; i < num_params; ++i) {
    if (!mxtpu_capi::set_str_item(keys, i, param_keys[i]) ||
        !mxtpu_capi::set_str_item(vals, i, param_vals[i])) {
      Py_DECREF(keys);
      Py_DECREF(vals);
      set_error_from_python();
      return -1;
    }
  }
  // reference in-place contract: a non-null *outputs with *num_outputs>0
  // means the caller provides preallocated arrays the op writes into
  // (the sgd_update-on-weight idiom); pass them through as `out=`
  bool inplace = (*outputs != nullptr && *num_outputs > 0);
  PyObject *given = Py_None;
  if (inplace) {
    given = PyList_New(*num_outputs);
    if (given == nullptr) {
      Py_DECREF(ins);
      Py_DECREF(keys);
      Py_DECREF(vals);
      set_error_from_python();
      return -1;
    }
    for (int i = 0; i < *num_outputs; ++i) {
      PyObject *o = nd((*outputs)[i])->obj;
      Py_INCREF(o);
      PyList_SET_ITEM(given, i, o);
    }
  } else {
    Py_INCREF(Py_None);
  }
  PyObject *args = Py_BuildValue("(sOOOO)", op_name, ins, keys, vals,
                                 given);
  Py_DECREF(ins);
  Py_DECREF(keys);
  Py_DECREF(vals);
  Py_DECREF(given);
  PyObject *res = args ? call_bridge("_capi_invoke", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  if (inplace) {
    // outputs written in place; caller's handles/spine stay untouched
    Py_DECREF(res);
    return 0;
  }
  Py_ssize_t n = PyList_Size(res);
  auto **outs = new NDArrayHandle[n];
  for (Py_ssize_t i = 0; i < n; ++i) {
    ND *h = new ND();
    h->obj = PyList_GetItem(res, i);
    Py_INCREF(h->obj);
    outs[i] = h;
  }
  Py_DECREF(res);
  *num_outputs = static_cast<int>(n);
  *outputs = outs;  // caller frees each handle (MXNDArrayFree) and the
                    // spine via MXImperativeInvokeSpineFree (reference
                    // stores the spine in thread-local ret space —
                    // documented divergence)
  return 0;
}

int MXImperativeInvokeSpineFree(NDArrayHandle *outputs) {
  delete[] outputs;
  return 0;
}

int MXAutogradSetIsRecording(int is_recording, int *prev) {
  GIL gil;
  PyObject *args = Py_BuildValue("(i)", is_recording);
  PyObject *res = args ? call_bridge("_capi_autograd_set_recording", args)
                       : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXAutogradSetIsTraining(int is_training, int *prev) {
  GIL gil;
  PyObject *args = Py_BuildValue("(i)", is_training);
  PyObject *res = args ? call_bridge("_capi_autograd_set_training", args)
                       : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  if (prev != nullptr) *prev = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle *var_handles,
                            mx_uint *reqs_array,
                            NDArrayHandle *grad_handles) {
  GIL gil;
  PyObject *vars = PyList_New(num_var);
  PyObject *reqs = PyList_New(num_var);
  PyObject *grads = PyList_New(num_var);
  if (vars == nullptr || reqs == nullptr || grads == nullptr) {
    Py_XDECREF(vars);
    Py_XDECREF(reqs);
    Py_XDECREF(grads);
    set_error_from_python();
    return -1;
  }
  for (mx_uint i = 0; i < num_var; ++i) {
    PyObject *v = nd(var_handles[i])->obj;
    PyObject *g = nd(grad_handles[i])->obj;
    Py_INCREF(v);
    Py_INCREF(g);
    PyList_SET_ITEM(vars, i, v);
    PyList_SET_ITEM(reqs, i, PyLong_FromUnsignedLong(reqs_array[i]));
    PyList_SET_ITEM(grads, i, g);
  }
  PyObject *args = Py_BuildValue("(OOO)", vars, reqs, grads);
  Py_DECREF(vars);
  Py_DECREF(reqs);
  Py_DECREF(grads);
  PyObject *res = args ? call_bridge("_capi_mark_variables", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int MXAutogradBackward(mx_uint num_output, NDArrayHandle *output_handles,
                       NDArrayHandle *ograd_handles, int retain_graph) {
  GIL gil;
  PyObject *outs = PyList_New(num_output);
  if (outs == nullptr) {
    set_error_from_python();
    return -1;
  }
  for (mx_uint i = 0; i < num_output; ++i) {
    PyObject *o = nd(output_handles[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(outs, i, o);
  }
  PyObject *ograds = Py_None;
  if (ograd_handles != nullptr) {
    ograds = PyList_New(num_output);
    if (ograds == nullptr) {
      Py_DECREF(outs);
      set_error_from_python();
      return -1;
    }
    for (mx_uint i = 0; i < num_output; ++i) {
      // a NULL entry means "default (ones) head gradient" in the
      // reference ABI; map it to None for the bridge
      PyObject *o = ograd_handles[i] != nullptr
                        ? nd(ograd_handles[i])->obj : Py_None;
      Py_INCREF(o);
      PyList_SET_ITEM(ograds, i, o);
    }
  } else {
    Py_INCREF(Py_None);
  }
  PyObject *args = Py_BuildValue("(OOi)", outs, ograds, retain_graph);
  Py_DECREF(outs);
  Py_DECREF(ograds);
  PyObject *res = args ? call_bridge("_capi_backward", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out) {
  *out = nullptr;
  ND *h = nd(handle);
  GIL gil;
  PyObject *res = call_bridge1("_capi_get_grad", h->obj);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  if (res == Py_None) {
    Py_DECREF(res);
    return 0;  // no grad attached: *out stays null (reference behavior)
  }
  ND *g = new ND();
  g->obj = res;
  *out = g;
  return 0;
}

// -- views / reshape / sync (reference c_api.cc NDArray block) --------------

// shared tail: wrap a bridge-returned NDArray into a fresh handle
static int nd_result(PyObject *res, NDArrayHandle *out) {
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  ND *h = new ND();
  h->obj = res;
  *out = h;
  return 0;
}

int MXNDArraySlice(NDArrayHandle handle, mx_uint slice_begin,
                   mx_uint slice_end, NDArrayHandle *out) {
  *out = nullptr;
  GIL gil;
  PyObject *args = Py_BuildValue("(OII)", nd(handle)->obj, slice_begin,
                                 slice_end);
  PyObject *res = args ? call_bridge("_capi_nd_slice", args) : nullptr;
  Py_XDECREF(args);
  return nd_result(res, out);
}

int MXNDArrayAt(NDArrayHandle handle, mx_uint idx, NDArrayHandle *out) {
  *out = nullptr;
  GIL gil;
  PyObject *args = Py_BuildValue("(OI)", nd(handle)->obj, idx);
  PyObject *res = args ? call_bridge("_capi_nd_at", args) : nullptr;
  Py_XDECREF(args);
  return nd_result(res, out);
}

int MXNDArrayReshape(NDArrayHandle handle, int ndim, int *dims,
                     NDArrayHandle *out) {
  *out = nullptr;
  GIL gil;
  PyObject *shape = PyList_New(ndim);
  if (shape == nullptr) {
    set_error_from_python();
    return -1;
  }
  for (int i = 0; i < ndim; ++i)
    PyList_SET_ITEM(shape, i, PyLong_FromLong(dims[i]));
  PyObject *args = Py_BuildValue("(ON)", nd(handle)->obj, shape);
  PyObject *res = args ? call_bridge("_capi_nd_reshape", args) : nullptr;
  Py_XDECREF(args);
  return nd_result(res, out);
}

int MXNDArrayGetStorageType(NDArrayHandle handle, int *out_storage_type) {
  GIL gil;
  PyObject *res = call_bridge1("_capi_nd_storage_type", nd(handle)->obj);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  *out_storage_type = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXNDArrayWaitToRead(NDArrayHandle handle) {
  GIL gil;
  PyObject *res = call_bridge1("_capi_nd_wait_to_read", nd(handle)->obj);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int MXNDArrayWaitAll() {
  GIL gil;
  PyObject *res = call_bridge("_capi_wait_all", nullptr);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

}  // extern "C"
