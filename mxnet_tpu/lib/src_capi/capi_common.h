// Shared plumbing for the flat C ABI translation units (predict +
// imperative). Embeds CPython: when the library is loaded from a Python
// process (ctypes) it attaches to the running interpreter; from a plain C
// host it initializes one. C++17 inline variables give every TU the same
// thread-local error slot, so MXGetLastError covers both API surfaces.
#ifndef MXTPU_CAPI_COMMON_H_
#define MXTPU_CAPI_COMMON_H_

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <mutex>
#include <string>
#include <vector>

typedef unsigned int mx_uint;
typedef float mx_float;

namespace mxtpu_capi {

inline thread_local std::string g_last_error;

inline void ensure_python() {
  static std::once_flag once;
  std::call_once(once, []() {
    if (!Py_IsInitialized()) {
      // plain-C host: bring up an interpreter and release the GIL so the
      // per-call PyGILState_Ensure below works from any thread
      Py_InitializeEx(0);
      // a sitecustomize PJRT hook may force jax onto accelerator hardware
      // at interpreter start; in an embedded interpreter no conftest can
      // re-assert the env's explicit JAX_PLATFORMS choice, and importing
      // the framework would dial (and potentially hang on) the tunnel —
      // honor the env var before anything imports jax-dependent modules
      PyRun_SimpleString(
          "import os\n"
          "if os.environ.get('JAX_PLATFORMS') == 'cpu':\n"
          "    import jax\n"
          "    jax.config.update('jax_platforms', 'cpu')\n");
      PyEval_SaveThread();
    }
  });
}

struct GIL {
  PyGILState_STATE st;
  GIL() {
    ensure_python();
    st = PyGILState_Ensure();
  }
  ~GIL() { PyGILState_Release(st); }
};

// capture the pending Python exception into the thread-local error slot
// (reference: c_api_error.cc MXAPISetLastError)
inline void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "unknown error";
  if (value != nullptr) {
    PyObject *s = PyObject_Str(value);
    if (s != nullptr) {
      const char *msg = PyUnicode_AsUTF8(s);
      if (msg != nullptr) g_last_error = msg;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// NDArrayHandle payload shared by every C-ABI translation unit (handles
// are allocated in one TU and freed in another — a single definition
// here keeps delete size/layout coherent by construction)
struct ND {
  PyObject *obj = nullptr;           // mxnet_tpu.ndarray.NDArray
  std::vector<mx_uint> shape;        // GetShape storage
  std::string bytes;                 // SyncCopyToCPU staging
};

// C string -> Python str via the filesystem default codec
// (surrogateescape round-trips non-UTF-8 bytes — Linux paths and op
// attr values are NOT guaranteed UTF-8; a raw PyUnicode_FromString NULL
// stored into a list crashes the next traversal instead of erroring).
// Appends into `list` at `i`; false with the Python error set on failure.
inline bool set_str_item(PyObject *list, Py_ssize_t i, const char *s) {
  PyObject *u = PyUnicode_DecodeFSDefault(s != nullptr ? s : "");
  if (u == nullptr) return false;
  PyList_SET_ITEM(list, i, u);
  return true;
}

// call <module>.<fn>(*args) -> new ref or nullptr (exception set)
inline PyObject *call_module_fn(const char *module, const char *fn,
                                PyObject *args) {
  PyObject *mod = PyImport_ImportModule(module);
  if (mod == nullptr) return nullptr;
  PyObject *f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (f == nullptr) return nullptr;
  PyObject *res = PyObject_CallObject(f, args);
  Py_DECREF(f);
  return res;
}

}  // namespace mxtpu_capi

#endif  // MXTPU_CAPI_COMMON_H_
