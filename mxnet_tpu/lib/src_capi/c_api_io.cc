// Data-iterator section of the flat C ABI (reference: include/mxnet/
// c_api.h MXDataIter*, implemented by src/c_api/c_api.cc over the IO
// registry). Creator handles are interned iterator-name strings, the
// same scheme the op creators use; an iterator handle owns the Python
// DataIter plus its current batch.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <string>
#include <vector>

#include "capi_common.h"

typedef void *NDArrayHandle;
typedef void *DataIterHandle;
typedef void *DataIterCreator;

namespace {

using mxtpu_capi::GIL;
using mxtpu_capi::ND;
using mxtpu_capi::g_last_error;
using mxtpu_capi::set_error_from_python;

PyObject *bridge(const char *fn, PyObject *args) {
  return mxtpu_capi::call_module_fn("mxnet_tpu.capi_bridge", fn, args);
}

struct It {
  PyObject *obj = nullptr;  // bridge iterator state dict
};

It *it(DataIterHandle h) { return static_cast<It *>(h); }

int fail() {
  set_error_from_python();
  return -1;
}

// process-lifetime creator-name storage (mirrors c_api.cc op creators)
std::vector<std::string> *g_iter_names = nullptr;
std::vector<void *> *g_iter_creators = nullptr;

int ensure_iter_names() {
  GIL gil;
  if (g_iter_names != nullptr) return 0;
  PyObject *res = bridge("_capi_list_data_iters", nullptr);
  if (res == nullptr) return fail();
  auto *names = new std::vector<std::string>();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i)
    names->push_back(PyUnicode_AsUTF8(PyList_GetItem(res, i)));
  Py_DECREF(res);
  auto *creators = new std::vector<void *>();
  for (std::string &s : *names)
    creators->push_back(const_cast<char *>(s.c_str()));
  g_iter_creators = creators;
  g_iter_names = names;  // publish last
  return 0;
}

// a batch-array getter returning a fresh NDArrayHandle
int nd_getter(const char *fn, DataIterHandle handle, NDArrayHandle *out) {
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", it(handle)->obj);
  PyObject *res = args ? bridge(fn, args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  ND *h = new ND();
  h->obj = res;
  *out = h;
  return 0;
}

}  // namespace

extern "C" {

int MXListDataIters(mx_uint *out_size, DataIterCreator **out_array) {
  if (ensure_iter_names() != 0) return -1;
  *out_size = static_cast<mx_uint>(g_iter_creators->size());
  *out_array = g_iter_creators->data();
  return 0;
}

int MXDataIterGetIterInfo(DataIterCreator creator, const char **name,
                          const char **description, mx_uint *num_args,
                          const char ***arg_names,
                          const char ***arg_type_infos,
                          const char ***arg_descriptions) {
  *name = static_cast<const char *>(creator);
  if (description) *description = "";
  // per-arg metadata is introspectable from Python (help()); the C
  // surface reports none, like several reference iterators do
  if (num_args) *num_args = 0;
  if (arg_names) *arg_names = nullptr;
  if (arg_type_infos) *arg_type_infos = nullptr;
  if (arg_descriptions) *arg_descriptions = nullptr;
  return 0;
}

int MXDataIterCreateIter(DataIterCreator creator, mx_uint num_param,
                         const char **keys, const char **vals,
                         DataIterHandle *out) {
  GIL gil;
  PyObject *ks = PyList_New(num_param);
  PyObject *vs = PyList_New(num_param);
  if (ks == nullptr || vs == nullptr) return fail();
  for (mx_uint i = 0; i < num_param; ++i) {
    // surrogateescape round-trips non-UTF-8 bytes (Linux paths are not
    // guaranteed UTF-8); a NULL in the list would crash the bridge
    PyObject *k = PyUnicode_DecodeFSDefault(keys[i]);
    PyObject *v = PyUnicode_DecodeFSDefault(vals[i]);
    if (k == nullptr || v == nullptr) {
      Py_XDECREF(k);
      Py_XDECREF(v);
      Py_DECREF(ks);
      Py_DECREF(vs);
      return fail();
    }
    PyList_SET_ITEM(ks, i, k);
    PyList_SET_ITEM(vs, i, v);
  }
  PyObject *args = Py_BuildValue(
      "(sNN)", static_cast<const char *>(creator), ks, vs);
  PyObject *res = args ? bridge("_capi_iter_create", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  It *h = new It();
  h->obj = res;
  *out = h;
  return 0;
}

int MXDataIterFree(DataIterHandle handle) {
  if (handle == nullptr) return 0;
  GIL gil;
  Py_XDECREF(it(handle)->obj);
  delete it(handle);
  return 0;
}

int MXDataIterNext(DataIterHandle handle, int *out) {
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", it(handle)->obj);
  PyObject *res = args ? bridge("_capi_iter_next", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

int MXDataIterBeforeFirst(DataIterHandle handle) {
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", it(handle)->obj);
  PyObject *res = args ? bridge("_capi_iter_before_first", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Py_DECREF(res);
  return 0;
}

int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out) {
  return nd_getter("_capi_iter_get_data", handle, out);
}

int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out) {
  return nd_getter("_capi_iter_get_label", handle, out);
}

int MXDataIterGetPadNum(DataIterHandle handle, int *pad) {
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", it(handle)->obj);
  PyObject *res = args ? bridge("_capi_iter_get_pad", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  *pad = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

}  // extern "C"
