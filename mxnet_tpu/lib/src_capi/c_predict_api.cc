// Flat C ABI for deployment inference — the TPU-native equivalent of the
// reference's include/mxnet/c_predict_api.h (17 MXNET_DLL entry points,
// implemented in src/c_api/c_predict_api.cc). Signatures mirror the
// reference exactly so a C/C++ host written against libmxnet's predict API
// recompiles against libmxtpu_capi unchanged.
//
// Architecture: the reference's implementation binds a GraphExecutor; here
// each predictor handle owns a Python `mxnet_tpu.predict.Predictor` (whose
// forward is a cached XLA executable). The C layer embeds CPython: when
// loaded from a Python process (ctypes) it attaches to the running
// interpreter; when loaded from a plain C host it initializes one. All
// array marshalling crosses as raw bytes — the Python bridge functions
// (_capi_* in mxnet_tpu/predict.py) do the numpy work, so this file needs
// only the stable CPython ABI.
//
// Build: see mxnet_tpu/lib/native.py get_capi() — compiled separately from
// libmxtpu.so because only this library links libpython.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

#include "capi_common.h"

typedef void *PredictorHandle;
typedef void *NDListHandle;

namespace {

using mxtpu_capi::GIL;
using mxtpu_capi::g_last_error;
using mxtpu_capi::set_error_from_python;

// call mxnet_tpu.predict.<fn>(*args) -> new ref or nullptr (exception set)
PyObject *call_bridge(const char *fn, PyObject *args) {
  return mxtpu_capi::call_module_fn("mxnet_tpu.predict", fn, args);
}

struct Pred {
  PyObject *obj;                              // mxnet_tpu.predict.Predictor
  std::vector<std::vector<mx_uint>> shapes;   // GetOutputShape storage
};

struct NDList {
  std::vector<std::string> keys;
  std::vector<std::vector<mx_uint>> shapes;
  std::vector<std::string> data;  // float32 bytes, stable until Free
};

// build {name: shape_tuple} from the API's CSR-style shape encoding
PyObject *shapes_dict(mx_uint num, const char **keys,
                      const mx_uint *indptr, const mx_uint *data) {
  PyObject *d = PyDict_New();
  if (d == nullptr) return nullptr;
  for (mx_uint i = 0; i < num; ++i) {
    mx_uint lo = indptr[i], hi = indptr[i + 1];
    PyObject *shape = PyTuple_New(hi - lo);
    if (shape == nullptr) {
      Py_DECREF(d);
      return nullptr;
    }
    for (mx_uint j = lo; j < hi; ++j)
      PyTuple_SET_ITEM(shape, j - lo, PyLong_FromUnsignedLong(data[j]));
    if (PyDict_SetItemString(d, keys[i], shape) != 0) {
      Py_DECREF(shape);
      Py_DECREF(d);
      return nullptr;
    }
    Py_DECREF(shape);
  }
  return d;
}

int create_impl(const char *symbol_json_str, const void *param_bytes,
                int param_size, int dev_type, int dev_id,
                mx_uint num_input_nodes, const char **input_keys,
                const mx_uint *input_shape_indptr,
                const mx_uint *input_shape_data, mx_uint num_output_nodes,
                const char **output_keys, PredictorHandle *out) {
  *out = nullptr;
  GIL gil;
  PyObject *shapes = shapes_dict(num_input_nodes, input_keys,
                                 input_shape_indptr, input_shape_data);
  if (shapes == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *params;
  if (param_bytes != nullptr && param_size > 0) {
    params = PyBytes_FromStringAndSize(
        static_cast<const char *>(param_bytes), param_size);
  } else {
    params = Py_None;
    Py_INCREF(params);
  }
  PyObject *outputs;
  if (num_output_nodes > 0) {
    outputs = PyList_New(num_output_nodes);
    for (mx_uint i = 0; i < num_output_nodes; ++i) {
      if (!mxtpu_capi::set_str_item(outputs, i, output_keys[i])) {
        Py_DECREF(outputs);
        set_error_from_python();
        return -1;
      }
    }
  } else {
    outputs = Py_None;
    Py_INCREF(outputs);
  }
  PyObject *args = Py_BuildValue("(sOiiOO)", symbol_json_str, params,
                                 dev_type, dev_id, shapes, outputs);
  Py_DECREF(shapes);
  Py_DECREF(params);
  Py_DECREF(outputs);
  if (args == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *pred = call_bridge("_capi_create", args);
  Py_DECREF(args);
  if (pred == nullptr) {
    set_error_from_python();
    return -1;
  }
  Pred *h = new Pred();
  h->obj = pred;
  *out = h;
  return 0;
}

}  // namespace

extern "C" {

const char *MXGetLastError() { return g_last_error.c_str(); }

int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char **input_keys,
                 const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data, PredictorHandle *out) {
  return create_impl(symbol_json_str, param_bytes, param_size, dev_type,
                     dev_id, num_input_nodes, input_keys, input_shape_indptr,
                     input_shape_data, 0, nullptr, out);
}

int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id, mx_uint num_input_nodes,
                           const char **input_keys,
                           const mx_uint *input_shape_indptr,
                           const mx_uint *input_shape_data,
                           mx_uint num_output_nodes, const char **output_keys,
                           PredictorHandle *out) {
  return create_impl(symbol_json_str, param_bytes, param_size, dev_type,
                     dev_id, num_input_nodes, input_keys, input_shape_indptr,
                     input_shape_data, num_output_nodes, output_keys, out);
}

int MXPredCreateMultiThread(const char *symbol_json_str,
                            const void *param_bytes, int param_size,
                            int dev_type, int dev_id, mx_uint num_input_nodes,
                            const char **input_keys,
                            const mx_uint *input_shape_indptr,
                            const mx_uint *input_shape_data, int num_threads,
                            PredictorHandle *out) {
  // reference semantics (c_predict_api.cc:216): ONE parse of param_bytes
  // and one device copy of the weights, shared across every per-thread
  // predictor; only input/output buffers are private. The first predictor
  // is the prototype; the rest are shared-weight clones.
  auto cleanup = [&](int upto) {
    for (int j = 0; j < upto; ++j) {
      Pred *h = static_cast<Pred *>(out[j]);
      GIL gil;
      Py_DECREF(h->obj);
      delete h;
      out[j] = nullptr;
    }
  };
  if (num_threads <= 0) return 0;
  int rc = create_impl(symbol_json_str, param_bytes, param_size, dev_type,
                       dev_id, num_input_nodes, input_keys,
                       input_shape_indptr, input_shape_data, 0, nullptr,
                       &out[0]);
  if (rc != 0) return rc;
  Pred *proto = static_cast<Pred *>(out[0]);
  for (int i = 1; i < num_threads; ++i) {
    GIL gil;
    PyObject *args = Py_BuildValue("(O)", proto->obj);
    PyObject *res = args ? call_bridge("_capi_clone_shared", args) : nullptr;
    Py_XDECREF(args);
    if (res == nullptr) {
      set_error_from_python();
      cleanup(i);
      return -1;
    }
    Pred *nh = new Pred();
    nh->obj = res;
    out[i] = nh;
  }
  return 0;
}

int MXPredReshape(mx_uint num_input_nodes, const char **input_keys,
                  const mx_uint *input_shape_indptr,
                  const mx_uint *input_shape_data, PredictorHandle handle,
                  PredictorHandle *out) {
  *out = nullptr;
  Pred *h = static_cast<Pred *>(handle);
  GIL gil;
  PyObject *shapes = shapes_dict(num_input_nodes, input_keys,
                                 input_shape_indptr, input_shape_data);
  if (shapes == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *args = Py_BuildValue("(OO)", h->obj, shapes);
  Py_DECREF(shapes);
  PyObject *res = args ? call_bridge("_capi_reshape", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Pred *nh = new Pred();
  nh->obj = res;  // bridge returns the (rebound) predictor — new reference
  *out = nh;
  return 0;
}

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint **shape_data, mx_uint *shape_ndim) {
  Pred *h = static_cast<Pred *>(handle);
  GIL gil;
  PyObject *args = Py_BuildValue("(OI)", h->obj, index);
  PyObject *res = args ? call_bridge("_capi_output_shape", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_ssize_t n = PyTuple_Size(res);
  if (h->shapes.size() <= index) h->shapes.resize(index + 1);
  std::vector<mx_uint> &shp = h->shapes[index];
  shp.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    shp[i] = static_cast<mx_uint>(PyLong_AsUnsignedLong(
        PyTuple_GET_ITEM(res, i)));
  Py_DECREF(res);
  *shape_data = shp.data();
  *shape_ndim = static_cast<mx_uint>(n);
  return 0;
}

int MXPredSetInput(PredictorHandle handle, const char *key,
                   const mx_float *data, mx_uint size) {
  Pred *h = static_cast<Pred *>(handle);
  GIL gil;
  PyObject *raw = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data),
      static_cast<Py_ssize_t>(size) * sizeof(mx_float));
  if (raw == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *args = Py_BuildValue("(OsO)", h->obj, key, raw);
  Py_DECREF(raw);
  PyObject *res = args ? call_bridge("_capi_set_input", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int MXPredForward(PredictorHandle handle) {
  Pred *h = static_cast<Pred *>(handle);
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", h->obj);
  PyObject *res = args ? call_bridge("_capi_forward", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

int MXPredPartialForward(PredictorHandle handle, int step, int *step_left) {
  // the whole forward is ONE fused XLA executable — there is no per-layer
  // stepping to expose (reference walks GraphExecutor nodes). step 0 runs
  // everything; step_left reports 0 so the documented polling loop
  // (c_predict_api.h:210-217) terminates after one iteration.
  if (step == 0) {
    int rc = MXPredForward(handle);
    if (rc != 0) return rc;
  }
  *step_left = 0;
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, mx_uint index, mx_float *data,
                    mx_uint size) {
  Pred *h = static_cast<Pred *>(handle);
  GIL gil;
  PyObject *args = Py_BuildValue("(OI)", h->obj, index);
  PyObject *res = args ? call_bridge("_capi_get_output", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *raw = PyTuple_GET_ITEM(res, 0);
  Py_ssize_t nbytes = PyBytes_Size(raw);
  if (nbytes != static_cast<Py_ssize_t>(size) * sizeof(mx_float)) {
    g_last_error = "MXPredGetOutput: size mismatch (got " +
                   std::to_string(size) + " floats, output has " +
                   std::to_string(nbytes / sizeof(mx_float)) + ")";
    Py_DECREF(res);
    return -1;
  }
  std::memcpy(data, PyBytes_AsString(raw), nbytes);
  Py_DECREF(res);
  return 0;
}

int MXPredFree(PredictorHandle handle) {
  Pred *h = static_cast<Pred *>(handle);
  if (h == nullptr) return 0;
  {
    GIL gil;
    Py_DECREF(h->obj);
  }
  delete h;
  return 0;
}

int MXNDListCreate(const char *nd_file_bytes, int nd_file_size,
                   NDListHandle *out, mx_uint *out_length) {
  *out = nullptr;
  *out_length = 0;
  GIL gil;
  PyObject *raw = PyBytes_FromStringAndSize(nd_file_bytes, nd_file_size);
  if (raw == nullptr) {
    set_error_from_python();
    return -1;
  }
  PyObject *args = Py_BuildValue("(O)", raw);
  Py_DECREF(raw);
  PyObject *res = args ? call_bridge("_capi_ndlist", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) {
    set_error_from_python();
    return -1;
  }
  NDList *lst = new NDList();
  Py_ssize_t n = PyList_Size(res);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *item = PyList_GET_ITEM(res, i);  // (key, shape, bytes)
    lst->keys.emplace_back(PyUnicode_AsUTF8(PyTuple_GET_ITEM(item, 0)));
    PyObject *shape = PyTuple_GET_ITEM(item, 1);
    std::vector<mx_uint> shp(PyTuple_Size(shape));
    for (size_t j = 0; j < shp.size(); ++j)
      shp[j] = static_cast<mx_uint>(
          PyLong_AsUnsignedLong(PyTuple_GET_ITEM(shape, j)));
    lst->shapes.push_back(std::move(shp));
    PyObject *bytes = PyTuple_GET_ITEM(item, 2);
    lst->data.emplace_back(PyBytes_AsString(bytes), PyBytes_Size(bytes));
  }
  Py_DECREF(res);
  *out = lst;
  *out_length = static_cast<mx_uint>(n);
  return 0;
}

int MXNDListGet(NDListHandle handle, mx_uint index, const char **out_key,
                const mx_float **out_data, const mx_uint **out_shape,
                mx_uint *out_ndim) {
  NDList *lst = static_cast<NDList *>(handle);
  if (index >= lst->keys.size()) {
    g_last_error = "MXNDListGet: index out of range";
    return -1;
  }
  *out_key = lst->keys[index].c_str();
  *out_data = reinterpret_cast<const mx_float *>(lst->data[index].data());
  *out_shape = lst->shapes[index].data();
  *out_ndim = static_cast<mx_uint>(lst->shapes[index].size());
  return 0;
}

int MXNDListFree(NDListHandle handle) {
  delete static_cast<NDList *>(handle);
  return 0;
}

}  // extern "C"
