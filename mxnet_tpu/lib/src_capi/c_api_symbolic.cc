// Symbol + Executor + NDArray-IO sections of the flat C ABI (reference:
// include/mxnet/c_api.h, implemented by src/c_api/c_api_symbolic.cc and
// c_api_executor.cc). Together with c_api.cc's imperative core this makes
// the classic C workflow possible: discover creators, compose a symbolic
// graph, infer shapes, bind an executor, forward/backward, save/load
// NDArrays. Signatures follow the reference so C hosts recompile
// unchanged.
//
// Handle model mirrors c_api.cc: SymbolHandle owns a Python _SymRec
// (mxnet_tpu.capi_bridge), ExecutorHandle owns a Python Executor; every
// returned const char* / shape pointer is backed by storage owned by the
// handle it came from (valid until the next call on that handle, the
// reference's own contract).
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <map>
#include <string>
#include <vector>

#include "capi_common.h"

typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;
typedef void *AtomicSymbolCreator;

namespace {

using mxtpu_capi::GIL;
using mxtpu_capi::g_last_error;
using mxtpu_capi::set_error_from_python;

PyObject *bridge(const char *fn, PyObject *args) {
  return mxtpu_capi::call_module_fn("mxnet_tpu.capi_bridge", fn, args);
}

using mxtpu_capi::ND;  // shared handle layout (capi_common.h)

struct Sym {
  PyObject *obj = nullptr;            // _SymRec
  // string-list return storage (ListArguments/Outputs/Aux, GetAttr, JSON)
  std::vector<std::string> strs;
  std::vector<const char *> cstrs;
  std::string json;
  // InferShape return storage: flat dims + per-shape pointers
  std::vector<std::vector<mx_uint>> shp[3];
  std::vector<mx_uint> shp_ndim[3];
  std::vector<const mx_uint *> shp_ptr[3];
};

struct Exec {
  PyObject *obj = nullptr;            // mxnet_tpu.executor.Executor
  std::vector<NDArrayHandle> outputs;  // ND* handles (caller frees)
};

Sym *sym(SymbolHandle h) { return static_cast<Sym *>(h); }
Exec *ex(ExecutorHandle h) { return static_cast<Exec *>(h); }

int fail() {
  set_error_from_python();
  return -1;
}

// wrap a bridge call returning a _SymRec into a new SymbolHandle
int sym_out(PyObject *res, SymbolHandle *out) {
  if (res == nullptr) return fail();
  Sym *h = new Sym();
  h->obj = res;
  *out = h;
  return 0;
}

// expose a Python list of str through (size, char**) with handle storage
int str_list_out(Sym *h, PyObject *list, mx_uint *out_size,
                 const char ***out_array) {
  h->strs.clear();
  h->cstrs.clear();
  Py_ssize_t n = PyList_Size(list);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char *s = PyUnicode_AsUTF8(PyList_GetItem(list, i));
    h->strs.push_back(s ? s : "");
  }
  for (const std::string &s : h->strs) h->cstrs.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(h->strs.size());
  *out_array = h->cstrs.empty() ? nullptr : h->cstrs.data();
  return 0;
}

// Python list of shape tuples -> slot `which` of the handle's storage
void shapes_out(Sym *h, PyObject *list, int which, mx_uint *out_size,
                const mx_uint **out_ndim, const mx_uint ***out_data) {
  auto &shp = h->shp[which];
  auto &ndim = h->shp_ndim[which];
  auto &ptr = h->shp_ptr[which];
  shp.clear();
  ndim.clear();
  ptr.clear();
  Py_ssize_t n = PyList_Size(list);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *t = PyList_GetItem(list, i);
    std::vector<mx_uint> dims;
    if (t != Py_None && PySequence_Check(t)) {
      Py_ssize_t nd = PySequence_Size(t);
      for (Py_ssize_t d = 0; d < nd; ++d) {
        PyObject *v = PySequence_GetItem(t, d);
        dims.push_back(static_cast<mx_uint>(PyLong_AsUnsignedLong(v)));
        Py_XDECREF(v);
      }
    }
    shp.push_back(std::move(dims));
  }
  for (auto &s : shp) {
    ndim.push_back(static_cast<mx_uint>(s.size()));
    ptr.push_back(s.empty() ? nullptr : s.data());
  }
  *out_size = static_cast<mx_uint>(shp.size());
  *out_ndim = ndim.empty() ? nullptr : ndim.data();
  *out_data = ptr.empty() ? nullptr : ptr.data();
}

}  // namespace

extern "C" {

// -- symbol creation / composition ------------------------------------------

int MXSymbolCreateVariable(const char *name, SymbolHandle *out) {
  GIL gil;
  PyObject *args = Py_BuildValue("(s)", name);
  PyObject *res = args ? bridge("_capi_sym_create_variable", args) : nullptr;
  Py_XDECREF(args);
  return sym_out(res, out);
}

int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator creator,
                               mx_uint num_param, const char **keys,
                               const char **vals, SymbolHandle *out) {
  GIL gil;
  // creator handles ARE interned op-name strings (see c_api.cc)
  PyObject *ks = PyList_New(num_param);
  PyObject *vs = PyList_New(num_param);
  if (ks == nullptr || vs == nullptr) return fail();
  for (mx_uint i = 0; i < num_param; ++i) {
    if (!mxtpu_capi::set_str_item(ks, i, keys[i]) ||
        !mxtpu_capi::set_str_item(vs, i, vals[i])) {
      Py_DECREF(ks);
      Py_DECREF(vs);
      return fail();
    }
  }
  PyObject *args = Py_BuildValue("(sNN)",
                                 static_cast<const char *>(creator), ks, vs);
  PyObject *res = args ? bridge("_capi_sym_create_atomic", args) : nullptr;
  Py_XDECREF(args);
  return sym_out(res, out);
}

int MXSymbolCompose(SymbolHandle handle, const char *name, mx_uint num_args,
                    const char **keys, SymbolHandle *args_handles) {
  GIL gil;
  // all-keyword or all-positional, like the reference (a mixed key list
  // would mis-pair keys with inputs downstream — reject it loudly)
  mx_uint n_keyed = 0;
  for (mx_uint i = 0; i < num_args && keys != nullptr; ++i)
    if (keys[i] != nullptr && keys[i][0] != '\0') ++n_keyed;
  if (n_keyed != 0 && n_keyed != num_args) {
    g_last_error = "MXSymbolCompose: keys must be all-NULL (positional) "
                   "or all-set (keyword); mixed forms are not supported";
    return -1;
  }
  PyObject *ks = PyList_New(n_keyed);
  PyObject *ins = PyList_New(num_args);
  if (ks == nullptr || ins == nullptr) return fail();
  for (mx_uint i = 0; i < num_args; ++i) {
    if (n_keyed != 0 && !mxtpu_capi::set_str_item(ks, i, keys[i])) {
      Py_DECREF(ks);
      Py_DECREF(ins);
      return fail();
    }
    PyObject *o = sym(args_handles[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(ins, i, o);
  }
  PyObject *args = Py_BuildValue("(OsNN)", sym(handle)->obj,
                                 name ? name : "", ks, ins);
  PyObject *res = args ? bridge("_capi_sym_compose", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Py_DECREF(res);
  return 0;
}

int MXSymbolCopy(SymbolHandle handle, SymbolHandle *out) {
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", sym(handle)->obj);
  PyObject *res = args ? bridge("_capi_sym_copy", args) : nullptr;
  Py_XDECREF(args);
  return sym_out(res, out);
}

int MXSymbolCreateGroup(mx_uint num_symbols, SymbolHandle *symbols,
                        SymbolHandle *out) {
  GIL gil;
  PyObject *lst = PyList_New(num_symbols);
  if (lst == nullptr) return fail();
  for (mx_uint i = 0; i < num_symbols; ++i) {
    PyObject *o = sym(symbols[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(lst, i, o);
  }
  PyObject *args = Py_BuildValue("(N)", lst);
  PyObject *res = args ? bridge("_capi_sym_group", args) : nullptr;
  Py_XDECREF(args);
  return sym_out(res, out);
}

int MXSymbolGetInternals(SymbolHandle handle, SymbolHandle *out) {
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", sym(handle)->obj);
  PyObject *res = args ? bridge("_capi_sym_internals", args) : nullptr;
  Py_XDECREF(args);
  return sym_out(res, out);
}

int MXSymbolGetOutput(SymbolHandle handle, mx_uint index, SymbolHandle *out) {
  GIL gil;
  PyObject *args = Py_BuildValue("(OI)", sym(handle)->obj, index);
  PyObject *res = args ? bridge("_capi_sym_get_output", args) : nullptr;
  Py_XDECREF(args);
  return sym_out(res, out);
}

int MXSymbolFree(SymbolHandle handle) {
  if (handle == nullptr) return 0;
  GIL gil;
  Py_XDECREF(sym(handle)->obj);
  delete sym(handle);
  return 0;
}

// -- listing / serialization ------------------------------------------------

static int list_fn(const char *fn, SymbolHandle handle, mx_uint *out_size,
                   const char ***out_array) {
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", sym(handle)->obj);
  PyObject *res = args ? bridge(fn, args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  int rc = str_list_out(sym(handle), res, out_size, out_array);
  Py_DECREF(res);
  return rc;
}

int MXSymbolListArguments(SymbolHandle handle, mx_uint *out_size,
                          const char ***out_array) {
  return list_fn("_capi_sym_list_arguments", handle, out_size, out_array);
}

int MXSymbolListOutputs(SymbolHandle handle, mx_uint *out_size,
                        const char ***out_array) {
  return list_fn("_capi_sym_list_outputs", handle, out_size, out_array);
}

int MXSymbolListAuxiliaryStates(SymbolHandle handle, mx_uint *out_size,
                                const char ***out_array) {
  return list_fn("_capi_sym_list_aux", handle, out_size, out_array);
}

int MXSymbolSaveToJSON(SymbolHandle handle, const char **out_json) {
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", sym(handle)->obj);
  PyObject *res = args ? bridge("_capi_sym_tojson", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  const char *s = PyUnicode_AsUTF8(res);
  sym(handle)->json = s ? s : "";
  Py_DECREF(res);
  *out_json = sym(handle)->json.c_str();
  return 0;
}

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
  GIL gil;
  PyObject *args = Py_BuildValue("(s)", json);
  PyObject *res = args ? bridge("_capi_sym_from_json", args) : nullptr;
  Py_XDECREF(args);
  return sym_out(res, out);
}

// -- name / attributes ------------------------------------------------------

// (out, success) string getter sharing the handle's json storage slot
static int str_success_fn(const char *fn, SymbolHandle handle,
                          const char *key, const char **out, int *success) {
  GIL gil;
  PyObject *args = key
      ? Py_BuildValue("(Os)", sym(handle)->obj, key)
      : Py_BuildValue("(O)", sym(handle)->obj);
  PyObject *res = args ? bridge(fn, args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  const char *s = PyUnicode_AsUTF8(PyTuple_GetItem(res, 0));
  int ok = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(res, 1)));
  if (s == nullptr) {
    Py_DECREF(res);
    return fail();
  }
  sym(handle)->json = s;
  Py_DECREF(res);
  *success = ok;
  *out = ok ? sym(handle)->json.c_str() : nullptr;
  return 0;
}

int MXSymbolGetName(SymbolHandle handle, const char **out, int *success) {
  return str_success_fn("_capi_sym_get_name", handle, nullptr, out, success);
}

int MXSymbolGetAttr(SymbolHandle handle, const char *key, const char **out,
                    int *success) {
  return str_success_fn("_capi_sym_get_attr", handle, key, out, success);
}

int MXSymbolSetAttr(SymbolHandle handle, const char *key,
                    const char *value) {
  GIL gil;
  PyObject *args = Py_BuildValue("(Oss)", sym(handle)->obj, key,
                                 value ? value : "");
  PyObject *res = args ? bridge("_capi_sym_set_attr", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Py_DECREF(res);
  return 0;
}

// ListAttr returns 2*out_size strings (k, v, k, v, ...) per the
// reference contract; out_size counts PAIRS
static int list_attr_impl(SymbolHandle handle, int shallow,
                          mx_uint *out_size, const char ***out) {
  GIL gil;
  PyObject *args = Py_BuildValue("(Oi)", sym(handle)->obj, shallow);
  PyObject *res = args ? bridge("_capi_sym_list_attr", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  mx_uint flat = 0;
  int rc = str_list_out(sym(handle), res, &flat, out);
  Py_DECREF(res);
  *out_size = flat / 2;
  return rc;
}

int MXSymbolListAttr(SymbolHandle handle, mx_uint *out_size,
                     const char ***out) {
  return list_attr_impl(handle, 0, out_size, out);
}

int MXSymbolListAttrShallow(SymbolHandle handle, mx_uint *out_size,
                            const char ***out) {
  return list_attr_impl(handle, 1, out_size, out);
}

// -- creator introspection --------------------------------------------------

namespace {
// per-creator info storage, keyed by the interned name pointer (process
// lifetime, like the creator names themselves); cached so repeated
// queries (binding generators iterate all creators) don't leak
struct CreatorInfo {
  std::string desc, var_args;
  std::vector<std::string> strs;
  std::vector<const char *> names, types, descs;
};

std::map<const void *, CreatorInfo *> *g_creator_info = nullptr;
}  // namespace

int MXSymbolGetAtomicSymbolInfo(AtomicSymbolCreator creator,
                                const char **name, const char **description,
                                mx_uint *num_args, const char ***arg_names,
                                const char ***arg_type_infos,
                                const char ***arg_descriptions,
                                const char **key_var_num_args,
                                const char **return_type) {
  GIL gil;
  *name = static_cast<const char *>(creator);
  if (g_creator_info == nullptr)
    g_creator_info = new std::map<const void *, CreatorInfo *>();
  auto it = g_creator_info->find(creator);
  if (it != g_creator_info->end()) {
    CreatorInfo *info = it->second;
    *description = info->desc.c_str();
    *num_args = static_cast<mx_uint>(info->names.size());
    *arg_names = info->names.empty() ? nullptr : info->names.data();
    *arg_type_infos = info->types.empty() ? nullptr : info->types.data();
    *arg_descriptions = info->descs.empty() ? nullptr : info->descs.data();
    *key_var_num_args = info->var_args.c_str();
    if (return_type != nullptr) *return_type = "";
    return 0;
  }
  PyObject *args = Py_BuildValue("(s)", *name);
  PyObject *res = args ? bridge("_capi_atomic_symbol_info", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  auto *info = new CreatorInfo();
  const char *d = PyUnicode_AsUTF8(PyTuple_GetItem(res, 0));
  info->desc = d ? d : "";
  PyObject *nl = PyTuple_GetItem(res, 1);
  PyObject *tl = PyTuple_GetItem(res, 2);
  PyObject *dl = PyTuple_GetItem(res, 3);
  const char *va = PyUnicode_AsUTF8(PyTuple_GetItem(res, 4));
  info->var_args = va ? va : "";
  Py_ssize_t n = PyList_Size(nl);
  for (Py_ssize_t i = 0; i < n; ++i) {
    for (PyObject *lst : {nl, tl, dl}) {
      const char *s = PyUnicode_AsUTF8(PyList_GetItem(lst, i));
      info->strs.push_back(s ? s : "");
    }
  }
  // pointers are stable now: strs never reallocates again
  for (Py_ssize_t i = 0; i < n; ++i) {
    info->names.push_back(info->strs[3 * i].c_str());
    info->types.push_back(info->strs[3 * i + 1].c_str());
    info->descs.push_back(info->strs[3 * i + 2].c_str());
  }
  Py_DECREF(res);
  *description = info->desc.c_str();
  *num_args = static_cast<mx_uint>(n);
  *arg_names = info->names.empty() ? nullptr : info->names.data();
  *arg_type_infos = info->types.empty() ? nullptr : info->types.data();
  *arg_descriptions = info->descs.empty() ? nullptr : info->descs.data();
  *key_var_num_args = info->var_args.c_str();
  if (return_type != nullptr) *return_type = "";
  (*g_creator_info)[creator] = info;  // process-lifetime cache
  return 0;
}

// -- shape inference --------------------------------------------------------

static int infer_shape_impl(
    SymbolHandle handle, mx_uint num_args, const char **keys,
    const mx_uint *arg_ind_ptr, const mx_uint *arg_shape_data,
    mx_uint *in_shape_size, const mx_uint **in_shape_ndim,
    const mx_uint ***in_shape_data, mx_uint *out_shape_size,
    const mx_uint **out_shape_ndim, const mx_uint ***out_shape_data,
    mx_uint *aux_shape_size, const mx_uint **aux_shape_ndim,
    const mx_uint ***aux_shape_data, int *complete, int partial) {
  GIL gil;
  PyObject *ks = PyList_New(num_args);
  PyObject *shps = PyList_New(num_args);
  if (ks == nullptr || shps == nullptr) return fail();
  for (mx_uint i = 0; i < num_args; ++i) {
    if (!mxtpu_capi::set_str_item(
            ks, i, (keys != nullptr && keys[i] != nullptr) ? keys[i] : "")) {
      Py_DECREF(ks);
      Py_DECREF(shps);
      return fail();
    }
    mx_uint lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
    PyObject *t = PyList_New(hi - lo);
    for (mx_uint d = lo; d < hi; ++d)
      PyList_SET_ITEM(t, d - lo, PyLong_FromUnsignedLong(arg_shape_data[d]));
    PyList_SET_ITEM(shps, i, t);
  }
  PyObject *args = Py_BuildValue("(ONNi)", sym(handle)->obj, ks, shps,
                                 partial);
  PyObject *res = args ? bridge("_capi_sym_infer_shape", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Sym *h = sym(handle);
  shapes_out(h, PyTuple_GetItem(res, 0), 0, in_shape_size, in_shape_ndim,
             in_shape_data);
  shapes_out(h, PyTuple_GetItem(res, 1), 1, out_shape_size, out_shape_ndim,
             out_shape_data);
  shapes_out(h, PyTuple_GetItem(res, 2), 2, aux_shape_size, aux_shape_ndim,
             aux_shape_data);
  *complete = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(res, 3)));
  Py_DECREF(res);
  return 0;
}

int MXSymbolInferShape(SymbolHandle handle, mx_uint num_args,
                       const char **keys, const mx_uint *arg_ind_ptr,
                       const mx_uint *arg_shape_data, mx_uint *in_shape_size,
                       const mx_uint **in_shape_ndim,
                       const mx_uint ***in_shape_data,
                       mx_uint *out_shape_size,
                       const mx_uint **out_shape_ndim,
                       const mx_uint ***out_shape_data,
                       mx_uint *aux_shape_size,
                       const mx_uint **aux_shape_ndim,
                       const mx_uint ***aux_shape_data, int *complete) {
  return infer_shape_impl(handle, num_args, keys, arg_ind_ptr,
                          arg_shape_data, in_shape_size, in_shape_ndim,
                          in_shape_data, out_shape_size, out_shape_ndim,
                          out_shape_data, aux_shape_size, aux_shape_ndim,
                          aux_shape_data, complete, 0);
}

int MXSymbolInferShapePartial(
    SymbolHandle handle, mx_uint num_args, const char **keys,
    const mx_uint *arg_ind_ptr, const mx_uint *arg_shape_data,
    mx_uint *in_shape_size, const mx_uint **in_shape_ndim,
    const mx_uint ***in_shape_data, mx_uint *out_shape_size,
    const mx_uint **out_shape_ndim, const mx_uint ***out_shape_data,
    mx_uint *aux_shape_size, const mx_uint **aux_shape_ndim,
    const mx_uint ***aux_shape_data, int *complete) {
  return infer_shape_impl(handle, num_args, keys, arg_ind_ptr,
                          arg_shape_data, in_shape_size, in_shape_ndim,
                          in_shape_data, out_shape_size, out_shape_ndim,
                          out_shape_data, aux_shape_size, aux_shape_ndim,
                          aux_shape_data, complete, 1);
}

// -- executor ---------------------------------------------------------------

int MXExecutorBind(SymbolHandle symbol_handle, int dev_type, int dev_id,
                   mx_uint len, NDArrayHandle *in_args,
                   NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                   mx_uint aux_states_len, NDArrayHandle *aux_states,
                   ExecutorHandle *out) {
  GIL gil;
  PyObject *ins = PyList_New(len);
  PyObject *grads = PyList_New(len);
  PyObject *reqs = PyList_New(len);
  PyObject *auxs = PyList_New(aux_states_len);
  if (!ins || !grads || !reqs || !auxs) return fail();
  for (mx_uint i = 0; i < len; ++i) {
    PyObject *o = static_cast<ND *>(in_args[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(ins, i, o);
    PyObject *g = Py_None;
    if (arg_grad_store != nullptr && arg_grad_store[i] != nullptr)
      g = static_cast<ND *>(arg_grad_store[i])->obj;
    Py_INCREF(g);
    PyList_SET_ITEM(grads, i, g);
    PyList_SET_ITEM(reqs, i, PyLong_FromUnsignedLong(
        grad_req_type ? grad_req_type[i] : 1));
  }
  for (mx_uint i = 0; i < aux_states_len; ++i) {
    PyObject *o = static_cast<ND *>(aux_states[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(auxs, i, o);
  }
  PyObject *args = Py_BuildValue("(OiiNNNN)",
                                 sym(symbol_handle)->obj, dev_type, dev_id,
                                 ins, grads, reqs, auxs);
  PyObject *res = args ? bridge("_capi_executor_bind", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Exec *h = new Exec();
  h->obj = res;
  *out = h;
  return 0;
}

int MXExecutorForward(ExecutorHandle handle, int is_train) {
  GIL gil;
  PyObject *args = Py_BuildValue("(Oi)", ex(handle)->obj, is_train);
  PyObject *res = args ? bridge("_capi_executor_forward", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Py_DECREF(res);
  return 0;
}

int MXExecutorBackward(ExecutorHandle handle, mx_uint len,
                       NDArrayHandle *head_grads) {
  GIL gil;
  PyObject *hg;
  if (len == 0 || head_grads == nullptr) {
    hg = Py_None;
    Py_INCREF(hg);
  } else {
    hg = PyList_New(len);
    if (hg == nullptr) return fail();
    for (mx_uint i = 0; i < len; ++i) {
      PyObject *o = static_cast<ND *>(head_grads[i])->obj;
      Py_INCREF(o);
      PyList_SET_ITEM(hg, i, o);
    }
  }
  PyObject *args = Py_BuildValue("(ON)", ex(handle)->obj, hg);
  PyObject *res = args ? bridge("_capi_executor_backward", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Py_DECREF(res);
  return 0;
}

int MXExecutorOutputs(ExecutorHandle handle, mx_uint *out_size,
                      NDArrayHandle **out) {
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", ex(handle)->obj);
  PyObject *res = args ? bridge("_capi_executor_outputs", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Exec *h = ex(handle);
  h->outputs.clear();
  Py_ssize_t n = PyList_Size(res);
  for (Py_ssize_t i = 0; i < n; ++i) {
    ND *a = new ND();
    a->obj = PyList_GetItem(res, i);
    Py_INCREF(a->obj);
    h->outputs.push_back(a);
  }
  Py_DECREF(res);
  *out_size = static_cast<mx_uint>(h->outputs.size());
  *out = h->outputs.empty() ? nullptr : h->outputs.data();
  return 0;
}

int MXExecutorFree(ExecutorHandle handle) {
  if (handle == nullptr) return 0;
  GIL gil;
  Py_XDECREF(ex(handle)->obj);
  delete ex(handle);
  return 0;
}

// -- NDArray save / load ----------------------------------------------------

int MXNDArraySave(const char *fname, mx_uint num_args, NDArrayHandle *args_h,
                  const char **keys) {
  GIL gil;
  PyObject *arrs = PyList_New(num_args);
  PyObject *ks = keys ? PyList_New(num_args) : Py_None;
  if (arrs == nullptr || ks == nullptr) return fail();
  if (ks == Py_None) Py_INCREF(ks);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyObject *o = static_cast<ND *>(args_h[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(arrs, i, o);
    if (keys && !mxtpu_capi::set_str_item(ks, i, keys[i])) {
      Py_DECREF(arrs);
      Py_DECREF(ks);
      return fail();
    }
  }
  PyObject *args = Py_BuildValue("(sNN)", fname, arrs, ks);
  PyObject *res = args ? bridge("_capi_nd_save", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Py_DECREF(res);
  return 0;
}

// load storage lives for the process (the reference keeps it on a
// thread-local ret store; a C host copies out promptly either way)
static std::vector<std::string> *g_load_names = nullptr;
static std::vector<const char *> *g_load_cstrs = nullptr;
static std::vector<NDArrayHandle> *g_load_handles = nullptr;

int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names) {
  GIL gil;
  PyObject *args = Py_BuildValue("(s)", fname);
  PyObject *res = args ? bridge("_capi_nd_load", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  PyObject *names = PyTuple_GetItem(res, 0);
  PyObject *arrs = PyTuple_GetItem(res, 1);
  delete g_load_names;
  delete g_load_cstrs;
  delete g_load_handles;
  g_load_names = new std::vector<std::string>();
  g_load_cstrs = new std::vector<const char *>();
  g_load_handles = new std::vector<NDArrayHandle>();
  for (Py_ssize_t i = 0; i < PyList_Size(names); ++i)
    g_load_names->push_back(PyUnicode_AsUTF8(PyList_GetItem(names, i)));
  for (const std::string &s : *g_load_names)
    g_load_cstrs->push_back(s.c_str());
  for (Py_ssize_t i = 0; i < PyList_Size(arrs); ++i) {
    ND *a = new ND();
    a->obj = PyList_GetItem(arrs, i);
    Py_INCREF(a->obj);
    g_load_handles->push_back(a);
  }
  Py_DECREF(res);
  *out_size = static_cast<mx_uint>(g_load_handles->size());
  *out_arr = g_load_handles->empty() ? nullptr : g_load_handles->data();
  *out_name_size = static_cast<mx_uint>(g_load_names->size());
  *out_names = g_load_cstrs->empty() ? nullptr : g_load_cstrs->data();
  return 0;
}

}  // extern "C"
