// KVStore section of the flat C ABI (reference: include/mxnet/c_api.h
// MXKVStore*, implemented by src/c_api/c_api.cc). Covers the classic
// data-parallel C workflow: create a store, init/push/pull keyed arrays,
// install a C updater callback, query rank/size, barrier.
//
// Handle model mirrors the other TUs: KVStoreHandle owns a Python
// mxnet_tpu.kvstore.KVStore. The updater callback crosses C -> Python ->
// C: MXKVStoreSetUpdater hands the function pointer (as uintptr) to the
// bridge, which wraps it with ctypes and re-materializes NDArrayHandles
// per call via mxtpu_capi_wrap_handle below.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <string>
#include <vector>

#include "capi_common.h"

typedef void *NDArrayHandle;
typedef void *KVStoreHandle;
typedef void (MXKVStoreUpdater)(int key, NDArrayHandle recv,
                                NDArrayHandle local, void *handle);

namespace {

using mxtpu_capi::GIL;
using mxtpu_capi::ND;
using mxtpu_capi::g_last_error;
using mxtpu_capi::set_error_from_python;

PyObject *bridge(const char *fn, PyObject *args) {
  return mxtpu_capi::call_module_fn("mxnet_tpu.capi_bridge", fn, args);
}

struct KV {
  PyObject *obj = nullptr;   // mxnet_tpu.kvstore.KVStore
  std::string type_storage;  // GetType return storage
};

KV *kv(KVStoreHandle h) { return static_cast<KV *>(h); }

int fail() {
  set_error_from_python();
  return -1;
}

// (keys_as_ints, nd_handles) -> (PyList[int], PyList[NDArray]) pair
int key_val_lists(mx_uint num, const int *keys, NDArrayHandle *vals,
                  PyObject **out_keys, PyObject **out_vals) {
  PyObject *ks = PyList_New(num);
  PyObject *vs = PyList_New(num);
  if (ks == nullptr || vs == nullptr) return -1;
  for (mx_uint i = 0; i < num; ++i) {
    PyList_SET_ITEM(ks, i, PyLong_FromLong(keys[i]));
    PyObject *o = static_cast<ND *>(vals[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(vs, i, o);
  }
  *out_keys = ks;
  *out_vals = vs;
  return 0;
}

// int-returning bridge call with one KVStore argument
int kv_int_fn(const char *fn, KVStoreHandle handle, int *out) {
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", kv(handle)->obj);
  PyObject *res = args ? bridge(fn, args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

}  // namespace

extern "C" {

// wrap a live Python NDArray (borrowed ref from the caller) into a fresh
// C handle — used by the bridge's updater trampoline; freed by the C
// host via MXNDArrayFree like any other handle
NDArrayHandle mxtpu_capi_wrap_handle(PyObject *obj) {
  GIL gil;  // ctypes releases the GIL around foreign calls
  ND *h = new ND();
  Py_INCREF(obj);
  h->obj = obj;
  return h;
}

int MXKVStoreCreate(const char *type, KVStoreHandle *out) {
  GIL gil;
  PyObject *args = Py_BuildValue("(s)", type ? type : "local");
  PyObject *res = args ? bridge("_capi_kv_create", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  KV *h = new KV();
  h->obj = res;
  *out = h;
  return 0;
}

int MXKVStoreFree(KVStoreHandle handle) {
  if (handle == nullptr) return 0;
  GIL gil;
  Py_XDECREF(kv(handle)->obj);
  delete kv(handle);
  return 0;
}

int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals) {
  GIL gil;
  PyObject *ks = nullptr, *vs = nullptr;
  if (key_val_lists(num, keys, vals, &ks, &vs) != 0) return fail();
  PyObject *args = Py_BuildValue("(ONN)", kv(handle)->obj, ks, vs);
  PyObject *res = args ? bridge("_capi_kv_init", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Py_DECREF(res);
  return 0;
}

int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority) {
  GIL gil;
  PyObject *ks = nullptr, *vs = nullptr;
  if (key_val_lists(num, keys, vals, &ks, &vs) != 0) return fail();
  PyObject *args = Py_BuildValue("(ONNi)", kv(handle)->obj, ks, vs,
                                 priority);
  PyObject *res = args ? bridge("_capi_kv_push", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Py_DECREF(res);
  return 0;
}

int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority) {
  GIL gil;
  PyObject *ks = nullptr, *vs = nullptr;
  if (key_val_lists(num, keys, vals, &ks, &vs) != 0) return fail();
  PyObject *args = Py_BuildValue("(ONNi)", kv(handle)->obj, ks, vs,
                                 priority);
  PyObject *res = args ? bridge("_capi_kv_pull", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Py_DECREF(res);
  return 0;
}

int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void *updater_handle) {
  GIL gil;
  PyObject *args = Py_BuildValue(
      "(OKK)", kv(handle)->obj,
      static_cast<unsigned long long>(
          reinterpret_cast<uintptr_t>(updater)),
      static_cast<unsigned long long>(
          reinterpret_cast<uintptr_t>(updater_handle)));
  PyObject *res = args ? bridge("_capi_kv_set_updater", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Py_DECREF(res);
  return 0;
}

int MXKVStoreGetType(KVStoreHandle handle, const char **type) {
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", kv(handle)->obj);
  PyObject *res = args ? bridge("_capi_kv_type", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  const char *s = PyUnicode_AsUTF8(res);
  if (s == nullptr) {  // non-str .type: report, don't leave the
    Py_DECREF(res);    // exception pending for an innocent later call
    return fail();
  }
  kv(handle)->type_storage = s;
  Py_DECREF(res);
  *type = kv(handle)->type_storage.c_str();
  return 0;
}

int MXKVStoreGetRank(KVStoreHandle handle, int *rank) {
  return kv_int_fn("_capi_kv_rank", handle, rank);
}

int MXKVStoreGetGroupSize(KVStoreHandle handle, int *size) {
  return kv_int_fn("_capi_kv_group_size", handle, size);
}

int MXKVStoreBarrier(KVStoreHandle handle) {
  GIL gil;
  PyObject *args = Py_BuildValue("(O)", kv(handle)->obj);
  PyObject *res = args ? bridge("_capi_kv_barrier", args) : nullptr;
  Py_XDECREF(args);
  if (res == nullptr) return fail();
  Py_DECREF(res);
  return 0;
}

}  // extern "C"
