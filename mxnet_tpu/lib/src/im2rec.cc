// Multithreaded im2rec packer (reference: tools/im2rec.cc — its speed comes
// from N worker threads preparing records in parallel while one thread
// writes them in .lst order). TPU-native scope: the fast path packs the
// ORIGINAL image bytes (no recode), which is the common dataset-pack case;
// resize/quality recoding stays in the Python driver (tools/im2rec.py).
//
// On-disk format interops with mxnet_tpu/recordio.py and the reference:
//   record  = uint32 magic 0xced7230a, uint32 lrec (low 29 bits = length),
//             payload, zero-pad to 4 bytes
//   payload = IRHeader{uint32 flag; float label; uint64 id; uint64 id2}
//             [+ flag * float32 labels when flag > 0] + image bytes
//   idx     = "id\toffset\n" per record, .lst order
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

// record framing is recordio.cc's writer (same library) — ONE
// implementation of the magic/length/padding format
extern "C" {
void* mxtpu_recio_writer_open(const char* path);
int64_t mxtpu_recio_writer_tell(void* handle);
int mxtpu_recio_writer_write(void* handle, const char* data, uint64_t len);
void mxtpu_recio_writer_close(void* handle);
}

namespace {

// a single record's length field is 29 bits (dmlc lrec); larger payloads
// would silently corrupt the stream under the writer's mask
constexpr uint64_t kMaxRecord = (1ull << 29) - 1;

struct PackItem {
  uint64_t id = 0;
  std::vector<float> labels;
  std::string path;
};

bool parse_lst(const char* lst_path, const char* root,
               std::vector<PackItem>* items) {
  FILE* f = std::fopen(lst_path, "r");
  if (!f) return false;
  std::string line;
  char buf[1 << 16];
  bool more = true;
  while (more) {
    // accumulate until newline/EOF: lines can exceed any fixed buffer
    // (detection lists carry thousands of float labels per line)
    line.clear();
    while (true) {
      if (!std::fgets(buf, sizeof(buf), f)) {
        more = false;
        break;
      }
      line += buf;
      if (!line.empty() && line.back() == '\n') break;
    }
    // match Python's line.strip(): trim whitespace at both ends
    size_t b = line.find_first_not_of(" \t\r\n");
    size_t e = line.find_last_not_of(" \t\r\n");
    line = (b == std::string::npos) ? std::string()
                                    : line.substr(b, e - b + 1);
    if (line.empty()) continue;
    std::vector<std::string> parts;
    size_t start = 0;
    while (true) {
      size_t tab = line.find('\t', start);
      parts.push_back(line.substr(start, tab - start));
      if (tab == std::string::npos) break;
      start = tab + 1;
    }
    if (parts.size() < 3) continue;
    PackItem it;
    char *end = nullptr;
    it.id = std::strtoull(parts[0].c_str(), &end, 10);
    if (end == parts[0].c_str() || *end != '\0') {
      // malformed id column: fail like the Python packer's int() raise
      // rather than silently packing id=0 (duplicate .idx keys)
      std::fclose(f);
      return false;
    }
    for (size_t i = 1; i + 1 < parts.size(); ++i)
      it.labels.push_back(std::strtof(parts[i].c_str(), nullptr));
    it.path = std::string(root);
    if (!it.path.empty() && it.path.back() != '/') it.path += '/';
    it.path += parts.back();
    items->push_back(std::move(it));
  }
  std::fclose(f);
  return true;
}

// payload = IRHeader + labels + file bytes; empty string on read failure
bool build_payload(const PackItem& it, std::string* out) {
  FILE* f = std::fopen(it.path.c_str(), "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long sz = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (sz < 0) { std::fclose(f); return false; }
  uint32_t flag = 0;
  float label = 0.f;
  size_t extra = 0;
  if (it.labels.size() == 1) {
    label = it.labels[0];
  } else {
    flag = static_cast<uint32_t>(it.labels.size());
    extra = it.labels.size() * sizeof(float);
  }
  const size_t header = 4 + 4 + 8 + 8;
  out->resize(header + extra + static_cast<size_t>(sz));
  char* p = &(*out)[0];
  uint64_t id = it.id, id2 = 0;
  std::memcpy(p, &flag, 4);
  std::memcpy(p + 4, &label, 4);
  std::memcpy(p + 8, &id, 8);
  std::memcpy(p + 16, &id2, 8);
  if (extra) std::memcpy(p + header, it.labels.data(), extra);
  size_t got = std::fread(p + header + extra, 1, static_cast<size_t>(sz), f);
  std::fclose(f);
  return got == static_cast<size_t>(sz);
}

}  // namespace

extern "C" {

// Pack lst -> rec + idx with num_threads payload builders. Returns the
// record count; -(1 + index_of_first_failed_item) for a per-item read
// failure; INT64_MIN for file-level failures (open or write errors on
// lst/rec/idx — write errors must NOT report success: a full disk would
// otherwise leave a silently truncated .rec behind).
int64_t mxtpu_im2rec_pack(const char* lst_path, const char* root,
                          const char* rec_path, const char* idx_path,
                          int num_threads) {
  constexpr int64_t kFileError = INT64_MIN;
  std::vector<PackItem> items;
  if (!parse_lst(lst_path, root, &items)) return kFileError;
  const size_t n = items.size();
  if (num_threads < 1) num_threads = 1;
  const size_t window = static_cast<size_t>(num_threads) * 8 + 8;

  std::vector<std::string> payloads(n);
  std::vector<char> ready(n, 0);
  std::vector<char> failed(n, 0);
  std::atomic<size_t> next{0};
  std::atomic<size_t> written{0};
  std::mutex mu;
  std::condition_variable cv_ready, cv_window;

  auto worker = [&]() {
    while (true) {
      size_t i = next.fetch_add(1);
      if (i >= n) break;
      {
        // bound memory: stay within `window` of the writer
        std::unique_lock<std::mutex> lock(mu);
        cv_window.wait(lock,
                       [&] { return i < written.load() + window; });
      }
      bool ok = build_payload(items[i], &payloads[i]);
      {
        std::lock_guard<std::mutex> lock(mu);
        if (!ok) failed[i] = 1;
        ready[i] = 1;
        cv_ready.notify_all();
      }
    }
  };

  std::vector<std::thread> threads;
  for (int t = 0; t < num_threads; ++t) threads.emplace_back(worker);

  void* rec = mxtpu_recio_writer_open(rec_path);
  FILE* idx = std::fopen(idx_path, "w");
  int64_t result = static_cast<int64_t>(n);
  if (!rec || !idx) {
    result = kFileError;
  } else {
    for (size_t i = 0; i < n; ++i) {
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_ready.wait(lock, [&] { return ready[i] != 0; });
        if (failed[i]) {
          result = -static_cast<int64_t>(i) - 1;
        }
      }
      if (result < 0) break;
      const std::string& payload = payloads[i];
      if (payload.size() > kMaxRecord) {
        result = -static_cast<int64_t>(i) - 1;
        break;
      }
      int64_t offset = mxtpu_recio_writer_tell(rec);
      bool ok =
          offset >= 0 &&
          mxtpu_recio_writer_write(rec, payload.data(),
                                   payload.size()) == 0 &&
          std::fprintf(idx, "%llu\t%llu\n",
                       static_cast<unsigned long long>(items[i].id),
                       static_cast<unsigned long long>(offset)) > 0;
      if (!ok) {
        result = kFileError;
        break;
      }
      {
        std::lock_guard<std::mutex> lock(mu);
        payloads[i].clear();
        payloads[i].shrink_to_fit();
        written.store(i + 1);
        cv_window.notify_all();
      }
    }
    if (result >= 0 && std::fflush(idx) != 0) {
      result = kFileError;
    }
  }
  {
    // unblock any worker still waiting on the window after an early stop
    std::lock_guard<std::mutex> lock(mu);
    written.store(n);
    cv_window.notify_all();
  }
  next.store(n);
  for (auto& t : threads) t.join();
  if (rec) mxtpu_recio_writer_close(rec);
  if (idx) std::fclose(idx);
  return result;
}

}  // extern "C"
