// Host staging-buffer pool.
//
// TPU-native equivalent of the reference's pooled storage managers
// (src/storage/pooled_storage_manager.h:52 GPUPooledStorageManager — best-fit
// size-class recycling). Device memory is owned by PJRT/XLA in this build;
// what remains hot on the host is the input-pipeline staging path, which
// wants recycled, aligned allocations instead of malloc/free per batch.
//
// C ABI (ctypes): mxtpu_pool_* — 64-byte aligned blocks recycled by
// round-up-to-power-of-two size class, like the reference's "Round" pool
// (GPUPooledRoundedStorageManager pooled_storage_manager.h:206).
#include <cstdint>
#include <cstdlib>
#include <map>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

struct Pool {
  std::mutex mu;
  // size-class (power of two) -> free blocks
  std::map<size_t, std::vector<void*>> free_blocks;
  // live ptr -> size-class
  std::unordered_map<void*, size_t> live;
  size_t bytes_allocated = 0;  // cumulative from the OS
  size_t bytes_live = 0;
  size_t hits = 0, misses = 0;

  ~Pool() {
    for (auto& kv : free_blocks)
      for (void* p : kv.second) std::free(p);
  }
};

Pool g_pool;

size_t round_class(size_t n) {
  size_t c = 64;
  while (c < n) c <<= 1;
  return c;
}

}  // namespace

extern "C" {

void* mxtpu_pool_alloc(size_t nbytes) {
  size_t cls = round_class(nbytes);
  std::lock_guard<std::mutex> lock(g_pool.mu);
  auto it = g_pool.free_blocks.find(cls);
  void* p = nullptr;
  if (it != g_pool.free_blocks.end() && !it->second.empty()) {
    p = it->second.back();
    it->second.pop_back();
    g_pool.hits++;
  } else {
    if (posix_memalign(&p, 64, cls) != 0) return nullptr;
    g_pool.bytes_allocated += cls;
    g_pool.misses++;
  }
  g_pool.live[p] = cls;
  g_pool.bytes_live += cls;
  return p;
}

void mxtpu_pool_free(void* p) {
  if (p == nullptr) return;
  std::lock_guard<std::mutex> lock(g_pool.mu);
  auto it = g_pool.live.find(p);
  if (it == g_pool.live.end()) {
    // unknown pointer (foreign alloc or double free): ignore — freeing here
    // would corrupt the heap if the block is already back in free_blocks
    return;
  }
  size_t cls = it->second;
  g_pool.live.erase(it);
  g_pool.bytes_live -= cls;
  g_pool.free_blocks[cls].push_back(p);
}

// release cached free blocks back to the OS (reference: DirectFree /
// empty_cache semantics, storage.cc)
void mxtpu_pool_trim() {
  std::lock_guard<std::mutex> lock(g_pool.mu);
  for (auto& kv : g_pool.free_blocks) {
    for (void* p : kv.second) {
      std::free(p);
      g_pool.bytes_allocated -= kv.first;
    }
    kv.second.clear();
  }
}

void mxtpu_pool_stats(uint64_t* allocated, uint64_t* live, uint64_t* hits,
                      uint64_t* misses) {
  std::lock_guard<std::mutex> lock(g_pool.mu);
  *allocated = g_pool.bytes_allocated;
  *live = g_pool.bytes_live;
  *hits = g_pool.hits;
  *misses = g_pool.misses;
}

}  // extern "C"
