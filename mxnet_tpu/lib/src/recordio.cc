// RecordIO: native reader/writer for the dmlc-core on-disk format.
//
// TPU-native equivalent of the reference's recordio path
// (dmlc-core RecordIOReader/Writer used by src/io/iter_image_recordio*.cc;
// format: uint32 magic 0xced7230a, uint32 lrec (low 29 bits = length),
// payload padded to 4 bytes — mirrored by python/mxnet/recordio.py).
// The Python front (mxnet_tpu/recordio.py) uses this automatically when the
// library builds; a pure-Python fallback keeps behavior identical without it.
//
// Also provides a background-thread prefetching reader: a bounded ring of
// record buffers filled by a reader thread — the role of the reference's
// iter_prefetcher.h double-buffering, applied at the record level.
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Reader {
  FILE* f = nullptr;
  std::vector<char> buf;
  bool error = false;
  std::string error_msg;
};

struct Writer {
  FILE* f = nullptr;
};

// -------- prefetching reader ------------------------------------------------

struct Prefetcher {
  FILE* f = nullptr;
  size_t capacity = 16;
  std::deque<std::vector<char>> queue;
  std::vector<char> current;
  std::mutex mu;
  std::condition_variable cv_pop, cv_push;
  std::thread worker;
  std::atomic<bool> stop{false};
  bool eof = false;
  bool error = false;

  void run() {
    while (!stop.load()) {
      uint32_t head[2];
      std::vector<char> rec;
      if (std::fread(head, sizeof(uint32_t), 2, f) != 2) {
        break;  // EOF
      }
      if (head[0] != kMagic) {
        error = true;
        break;
      }
      size_t len = head[1] & kLenMask;
      rec.resize(len);
      if (len && std::fread(rec.data(), 1, len, f) != len) {
        error = true;
        break;
      }
      size_t pad = (4 - len % 4) % 4;
      if (pad) std::fseek(f, static_cast<long>(pad), SEEK_CUR);
      std::unique_lock<std::mutex> lock(mu);
      cv_push.wait(lock, [&] { return queue.size() < capacity || stop.load(); });
      if (stop.load()) break;
      queue.push_back(std::move(rec));
      cv_pop.notify_one();
    }
    std::lock_guard<std::mutex> lock(mu);
    eof = true;
    cv_pop.notify_all();
  }
};

}  // namespace

extern "C" {

// -------- sequential reader -------------------------------------------------

void* mxtpu_recio_reader_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  auto* r = new Reader();
  r->f = f;
  return r;
}

// Status: 1 = record read (len/data set), 0 = EOF, -1 = corrupt stream.
// Zero-length records are valid (status 1, *len 0), hence the separate
// status — *data points into an internal buffer valid until the next call.
int mxtpu_recio_reader_next(void* handle, const char** data, uint64_t* len) {
  auto* r = static_cast<Reader*>(handle);
  uint32_t head[2];
  if (std::fread(head, sizeof(uint32_t), 2, r->f) != 2) return 0;
  if (head[0] != kMagic) {
    r->error = true;
    return -1;
  }
  size_t n = head[1] & kLenMask;
  r->buf.resize(n);
  if (n && std::fread(r->buf.data(), 1, n, r->f) != n) {
    r->error = true;
    return -1;
  }
  size_t pad = (4 - n % 4) % 4;
  if (pad) std::fseek(r->f, static_cast<long>(pad), SEEK_CUR);
  *data = r->buf.data();
  *len = n;
  return 1;
}

int mxtpu_recio_reader_read_at(void* handle, uint64_t pos, const char** data,
                               uint64_t* len) {
  auto* r = static_cast<Reader*>(handle);
  if (std::fseek(r->f, static_cast<long>(pos), SEEK_SET) != 0) return -1;
  return mxtpu_recio_reader_next(handle, data, len);
}

int64_t mxtpu_recio_reader_tell(void* handle) {
  return std::ftell(static_cast<Reader*>(handle)->f);
}

void mxtpu_recio_reader_reset(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  std::fseek(r->f, 0, SEEK_SET);
}

void mxtpu_recio_reader_close(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  if (r->f) std::fclose(r->f);
  delete r;
}

// -------- writer ------------------------------------------------------------

void* mxtpu_recio_writer_open(const char* path) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  return w;
}

int64_t mxtpu_recio_writer_tell(void* handle) {
  return std::ftell(static_cast<Writer*>(handle)->f);
}

int mxtpu_recio_writer_write(void* handle, const char* data, uint64_t len) {
  auto* w = static_cast<Writer*>(handle);
  uint32_t head[2] = {kMagic, static_cast<uint32_t>(len & kLenMask)};
  if (std::fwrite(head, sizeof(uint32_t), 2, w->f) != 2) return -1;
  if (len && std::fwrite(data, 1, len, w->f) != len) return -1;
  size_t pad = (4 - len % 4) % 4;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad && std::fwrite(zeros, 1, pad, w->f) != pad) return -1;
  return 0;
}

void mxtpu_recio_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  if (w->f) std::fclose(w->f);
  delete w;
}

// -------- prefetching reader ------------------------------------------------

void* mxtpu_prefetch_open(const char* path, uint64_t capacity) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  auto* p = new Prefetcher();
  p->f = f;
  if (capacity) p->capacity = capacity;
  p->worker = std::thread([p] { p->run(); });
  return p;
}

// Pops the next record (blocking). Status: 1 = record, 0 = EOF, -1 = error.
// *data valid until the next pop on this handle.
int mxtpu_prefetch_next(void* handle, const char** data, uint64_t* len) {
  auto* p = static_cast<Prefetcher*>(handle);
  std::unique_lock<std::mutex> lock(p->mu);
  p->cv_pop.wait(lock, [&] { return !p->queue.empty() || p->eof; });
  if (p->queue.empty()) return p->error ? -1 : 0;
  p->current = std::move(p->queue.front());
  p->queue.pop_front();
  p->cv_push.notify_one();
  *data = p->current.data();
  *len = p->current.size();
  return 1;
}

void mxtpu_prefetch_close(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  {
    // store stop under the mutex: a bare store+notify can land between the
    // worker's predicate check and its wait, and the wakeup is lost — the
    // worker then blocks forever and join() hangs
    std::lock_guard<std::mutex> lock(p->mu);
    p->stop.store(true);
  }
  p->cv_push.notify_all();
  if (p->worker.joinable()) p->worker.join();
  if (p->f) std::fclose(p->f);
  delete p;
}

}  // extern "C"
