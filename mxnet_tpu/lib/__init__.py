"""Native (C++) runtime components.

The reference's runtime around the compute path is C++ (engine, storage, IO —
SURVEY §2.1 N1/N2/N13). Here the TPU compute path is XLA, but the host-side
runtime pieces that remain hot — RecordIO parsing, the threaded prefetching
data pipeline, pinned host staging buffers — are likewise native C++
(`mxnet_tpu/lib/native/`), lazily compiled with g++ on first use and loaded
via ctypes. Everything has a pure-Python fallback so the framework still
works where no toolchain exists.
"""
from . import native  # noqa: F401
