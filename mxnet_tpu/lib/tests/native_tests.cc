// Sanitizer test driver for the native runtime (SURVEY §5.2: the
// reference's race strategy = engine var-dependency construction + ASAN CI
// builds, runtime_functions.sh:432-438. Our native surface is the C++
// recordio reader/writer, the threaded prefetcher, and the host buffer
// pool; this driver exercises them under ASan/UBSan/TSan via
// ci/sanitize.sh — pure C++, no Python, so sanitizer output is clean.)
//
// Build: see ci/sanitize.sh. Exit 0 = all checks passed and no sanitizer
// report (sanitizers abort the process on findings).
#include <atomic>
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* mxtpu_recio_writer_open(const char* path);
int64_t mxtpu_recio_writer_tell(void* handle);
int mxtpu_recio_writer_write(void* handle, const char* data, uint64_t len);
void mxtpu_recio_writer_close(void* handle);
void* mxtpu_recio_reader_open(const char* path);
int mxtpu_recio_reader_next(void* handle, const char** data, uint64_t* len);
int mxtpu_recio_reader_read_at(void* handle, uint64_t pos, const char** data,
                               uint64_t* len);
void mxtpu_recio_reader_reset(void* handle);
void mxtpu_recio_reader_close(void* handle);
void* mxtpu_prefetch_open(const char* path, uint64_t capacity);
int mxtpu_prefetch_next(void* handle, const char** data, uint64_t* len);
void mxtpu_prefetch_close(void* handle);
void* mxtpu_pool_alloc(size_t nbytes);
void mxtpu_pool_free(void* p);
void mxtpu_pool_trim();
void mxtpu_pool_stats(uint64_t* allocated, uint64_t* live, uint64_t* hits,
                      uint64_t* misses);
int64_t mxtpu_im2rec_pack(const char* lst_path, const char* root,
                          const char* rec_path, const char* idx_path,
                          int num_threads);
}

#define CHECK(cond)                                                       \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,       \
                   __LINE__, #cond);                                      \
      std::abort();                                                       \
    }                                                                     \
  } while (0)

static std::string write_file(const char* path, int n) {
  void* w = mxtpu_recio_writer_open(path);
  CHECK(w != nullptr);
  for (int i = 0; i < n; ++i) {
    std::string payload(100 + (i % 37) * 13, char('a' + i % 26));
    CHECK(mxtpu_recio_writer_write(w, payload.data(), payload.size()) == 0);
  }
  mxtpu_recio_writer_close(w);
  return path;
}

static void test_roundtrip(const char* path) {
  void* r = mxtpu_recio_reader_open(path);
  CHECK(r != nullptr);
  const char* data;
  uint64_t len;
  int count = 0;
  // status convention: 1 = record, 0 = EOF, -1 = corrupt
  while (mxtpu_recio_reader_next(r, &data, &len) == 1) {
    CHECK(len == 100 + (count % 37) * 13);
    CHECK(data[0] == char('a' + count % 26));
    ++count;
  }
  CHECK(count == 200);
  mxtpu_recio_reader_reset(r);
  CHECK(mxtpu_recio_reader_next(r, &data, &len) == 1);
  CHECK(len == 100);
  mxtpu_recio_reader_close(r);
}

static void test_prefetch_full_drain(const char* path) {
  void* p = mxtpu_prefetch_open(path, 8);
  CHECK(p != nullptr);
  const char* data;
  uint64_t len;
  int count = 0;
  while (mxtpu_prefetch_next(p, &data, &len) == 1) ++count;
  CHECK(count == 200);
  mxtpu_prefetch_close(p);
}

static void test_prefetch_early_close(const char* path) {
  // the lost-wakeup regression (ADVICE round-1): close while the worker
  // is blocked on a FULL queue must not hang. Loop it to give TSan/ASan
  // many interleavings.
  for (int it = 0; it < 50; ++it) {
    void* p = mxtpu_prefetch_open(path, 2);
    CHECK(p != nullptr);
    const char* data;
    uint64_t len;
    // consume a couple then abandon mid-stream
    for (int i = 0; i < it % 3; ++i) mxtpu_prefetch_next(p, &data, &len);
    mxtpu_prefetch_close(p);
  }
}

static void test_pool_concurrent() {
  std::atomic<int> errors{0};
  auto worker = [&](int seed) {
    std::vector<void*> held;
    for (int i = 0; i < 2000; ++i) {
      size_t sz = 64 + ((seed * 2654435761u + i * 40503u) % 8192);
      void* p = mxtpu_pool_alloc(sz);
      if (!p) { errors.fetch_add(1); continue; }
      std::memset(p, seed & 0xff, sz);  // touch the whole allocation
      held.push_back(p);
      if (held.size() > 16) {
        mxtpu_pool_free(held.front());
        held.erase(held.begin());
      }
    }
    for (void* p : held) mxtpu_pool_free(p);
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < 4; ++t) ts.emplace_back(worker, t + 1);
  for (auto& t : ts) t.join();
  CHECK(errors.load() == 0);
  mxtpu_pool_trim();
  uint64_t allocated, live, hits, misses;
  mxtpu_pool_stats(&allocated, &live, &hits, &misses);
  CHECK(live == 0);
}

static void test_im2rec_concurrent() {
  // 120 "images" packed by 4 worker threads + the in-order writer: the
  // window/condvar pipeline is the im2rec packer's race surface
  const char* root = "/tmp/mxtpu_im2rec_test";
  std::string cmd = std::string("rm -rf ") + root;
  CHECK(std::system(cmd.c_str()) == 0);
  cmd = std::string("mkdir -p ") + root;
  CHECK(std::system(cmd.c_str()) == 0);
  const int n = 120;
  {
    std::string lst = std::string(root) + "/ds.lst";
    FILE* lf = std::fopen(lst.c_str(), "w");
    CHECK(lf != nullptr);
    for (int i = 0; i < n; ++i) {
      char name[64];
      std::snprintf(name, sizeof(name), "img%03d.bin", i);
      std::string p = std::string(root) + "/" + name;
      FILE* f = std::fopen(p.c_str(), "wb");
      CHECK(f != nullptr);
      std::string payload(50 + (i % 17) * 31, char('A' + i % 26));
      CHECK(std::fwrite(payload.data(), 1, payload.size(), f)
            == payload.size());
      std::fclose(f);
      std::fprintf(lf, "%d\t%f\t%s\n", i, static_cast<double>(i % 5), name);
    }
    std::fclose(lf);
  }
  std::string lst = std::string(root) + "/ds.lst";
  std::string rec = std::string(root) + "/ds.rec";
  std::string idx = std::string(root) + "/ds.idx";
  int64_t got = mxtpu_im2rec_pack(lst.c_str(), root, rec.c_str(),
                                  idx.c_str(), 4);
  CHECK(got == n);
  // the rec stream parses back with the right record count + sizes
  void* r = mxtpu_recio_reader_open(rec.c_str());
  CHECK(r != nullptr);
  const char* data;
  uint64_t len;
  int count = 0;
  while (mxtpu_recio_reader_next(r, &data, &len) == 1) {
    const uint64_t header = 4 + 4 + 8 + 8;
    CHECK(len == header + 50 + (count % 17) * 31);
    ++count;
  }
  mxtpu_recio_reader_close(r);
  CHECK(count == n);
  // a malformed id column fails the whole pack (file-level error)
  {
    FILE* lf = std::fopen(lst.c_str(), "a");
    std::fprintf(lf, "notanum\t0.0\timg000.bin\n");
    std::fclose(lf);
  }
  CHECK(mxtpu_im2rec_pack(lst.c_str(), root, rec.c_str(), idx.c_str(), 2)
        < 0);
}

int main() {
  const char* path = "/tmp/mxtpu_native_test.rec";
  write_file(path, 200);
  test_roundtrip(path);
  test_prefetch_full_drain(path);
  test_prefetch_early_close(path);
  test_pool_concurrent();
  test_im2rec_concurrent();
  std::printf("NATIVE TESTS OK\n");
  return 0;
}
