/*
 * Minimal imperative flat C ABI (libmxtpu_capi.so) — the NDArray /
 * invoke / autograd core of the reference's include/mxnet/c_api.h,
 * proving non-Python bindings against the TPU-native runtime. Signatures
 * mirror the reference; see mxnet_tpu/lib/src_capi/c_api.cc for the two
 * documented divergences (creator handles are interned op-name strings;
 * MXImperativeInvoke output spines are caller-freed via
 * MXImperativeInvokeSpineFree).
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#ifdef __cplusplus
extern "C" {
#endif

#include <stddef.h>

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *NDArrayHandle;
typedef void *AtomicSymbolCreator;

const char *MXGetLastError();

int MXGetVersion(int *out);

int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle *out);
int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle *out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                             size_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data, size_t size);
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                      const mx_uint **out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype);
int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                        int *out_dev_id);
int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out);
int MXNDArraySlice(NDArrayHandle handle, mx_uint slice_begin,
                   mx_uint slice_end, NDArrayHandle *out);
int MXNDArrayAt(NDArrayHandle handle, mx_uint idx, NDArrayHandle *out);
int MXNDArrayReshape(NDArrayHandle handle, int ndim, int *dims,
                     NDArrayHandle *out);
int MXNDArrayGetStorageType(NDArrayHandle handle, int *out_storage_type);
int MXNDArrayWaitToRead(NDArrayHandle handle);
int MXNDArrayWaitAll();

int MXListAllOpNames(mx_uint *out_size, const char ***out_array);
int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                     AtomicSymbolCreator **out_array);
int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char **name);

/* Set *outputs = NULL / *num_outputs = 0 for fresh output allocation
 * (free the spine with MXImperativeInvokeSpineFree). A non-NULL *outputs
 * with *num_outputs > 0 is the reference's in-place contract: results are
 * written into the caller's preallocated arrays. */
int MXImperativeInvoke(AtomicSymbolCreator creator, int num_inputs,
                       NDArrayHandle *inputs, int *num_outputs,
                       NDArrayHandle **outputs, int num_params,
                       const char **param_keys, const char **param_vals);
int MXImperativeInvokeSpineFree(NDArrayHandle *outputs);

int MXAutogradSetIsRecording(int is_recording, int *prev);
int MXAutogradSetIsTraining(int is_training, int *prev);
int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle *var_handles,
                            mx_uint *reqs_array,
                            NDArrayHandle *grad_handles);
int MXAutogradBackward(mx_uint num_output, NDArrayHandle *output_handles,
                       NDArrayHandle *ograd_handles, int retain_graph);

/* -- symbol section (c_api_symbolic.cc; reference c_api.h symbol block).
 * A SymbolHandle from MXSymbolCreateAtomicSymbol is a node with no
 * inputs; MXSymbolCompose binds them. Returned string/shape pointers are
 * owned by the handle and stay valid until its next call. */
typedef void *SymbolHandle;
typedef void *ExecutorHandle;

int MXSymbolCreateVariable(const char *name, SymbolHandle *out);
int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator creator,
                               mx_uint num_param, const char **keys,
                               const char **vals, SymbolHandle *out);
int MXSymbolCompose(SymbolHandle sym, const char *name, mx_uint num_args,
                    const char **keys, SymbolHandle *args);
int MXSymbolCopy(SymbolHandle symbol, SymbolHandle *out);
int MXSymbolCreateGroup(mx_uint num_symbols, SymbolHandle *symbols,
                        SymbolHandle *out);
int MXSymbolGetInternals(SymbolHandle symbol, SymbolHandle *out);
int MXSymbolGetOutput(SymbolHandle symbol, mx_uint index, SymbolHandle *out);
int MXSymbolFree(SymbolHandle symbol);
int MXSymbolListArguments(SymbolHandle symbol, mx_uint *out_size,
                          const char ***out_str_array);
int MXSymbolListOutputs(SymbolHandle symbol, mx_uint *out_size,
                        const char ***out_str_array);
int MXSymbolListAuxiliaryStates(SymbolHandle symbol, mx_uint *out_size,
                                const char ***out_str_array);
int MXSymbolGetName(SymbolHandle symbol, const char **out, int *success);
int MXSymbolGetAttr(SymbolHandle symbol, const char *key, const char **out,
                    int *success);
int MXSymbolSetAttr(SymbolHandle symbol, const char *key,
                    const char *value);
int MXSymbolListAttr(SymbolHandle symbol, mx_uint *out_size,
                     const char ***out);  /* 2*out_size strings (k,v,...) */
int MXSymbolListAttrShallow(SymbolHandle symbol, mx_uint *out_size,
                            const char ***out);
int MXSymbolGetAtomicSymbolInfo(AtomicSymbolCreator creator,
                                const char **name, const char **description,
                                mx_uint *num_args, const char ***arg_names,
                                const char ***arg_type_infos,
                                const char ***arg_descriptions,
                                const char **key_var_num_args,
                                const char **return_type);
int MXSymbolSaveToJSON(SymbolHandle symbol, const char **out_json);
int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                       const char **keys, const mx_uint *arg_ind_ptr,
                       const mx_uint *arg_shape_data, mx_uint *in_shape_size,
                       const mx_uint **in_shape_ndim,
                       const mx_uint ***in_shape_data,
                       mx_uint *out_shape_size,
                       const mx_uint **out_shape_ndim,
                       const mx_uint ***out_shape_data,
                       mx_uint *aux_shape_size,
                       const mx_uint **aux_shape_ndim,
                       const mx_uint ***aux_shape_data, int *complete);
int MXSymbolInferShapePartial(
    SymbolHandle sym, mx_uint num_args, const char **keys,
    const mx_uint *arg_ind_ptr, const mx_uint *arg_shape_data,
    mx_uint *in_shape_size, const mx_uint **in_shape_ndim,
    const mx_uint ***in_shape_data, mx_uint *out_shape_size,
    const mx_uint **out_shape_ndim, const mx_uint ***out_shape_data,
    mx_uint *aux_shape_size, const mx_uint **aux_shape_ndim,
    const mx_uint ***aux_shape_data, int *complete);

/* -- executor (reference c_api_executor.cc Bind/Forward/Backward). Output
 * handles from MXExecutorOutputs are caller-freed via MXNDArrayFree. */
int MXExecutorBind(SymbolHandle symbol_handle, int dev_type, int dev_id,
                   mx_uint len, NDArrayHandle *in_args,
                   NDArrayHandle *arg_grad_store, mx_uint *grad_req_type,
                   mx_uint aux_states_len, NDArrayHandle *aux_states,
                   ExecutorHandle *out);
int MXExecutorForward(ExecutorHandle handle, int is_train);
int MXExecutorBackward(ExecutorHandle handle, mx_uint len,
                       NDArrayHandle *head_grads);
int MXExecutorOutputs(ExecutorHandle handle, mx_uint *out_size,
                      NDArrayHandle **out);
int MXExecutorFree(ExecutorHandle handle);

/* -- NDArray save/load (reference c_api.cc) */
int MXNDArraySave(const char *fname, mx_uint num_args,
                  NDArrayHandle *args, const char **keys);
int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                  NDArrayHandle **out_arr, mx_uint *out_name_size,
                  const char ***out_names);

/* -- kvstore (c_api_kvstore.cc; reference c_api.h MXKVStore block).
 * Per the reference MXKVStoreUpdater contract, the updater callback
 * OWNS the recv/local handles it receives and must free them with
 * MXNDArrayFree before returning. */
typedef void *KVStoreHandle;
typedef void (MXKVStoreUpdater)(int key, NDArrayHandle recv,
                                NDArrayHandle local, void *handle);

int MXKVStoreCreate(const char *type, KVStoreHandle *out);
int MXKVStoreFree(KVStoreHandle handle);
int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals);
int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int *keys,
                  NDArrayHandle *vals, int priority);
int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void *updater_handle);
int MXKVStoreGetType(KVStoreHandle handle, const char **type);
int MXKVStoreGetRank(KVStoreHandle handle, int *rank);
int MXKVStoreGetGroupSize(KVStoreHandle handle, int *size);
int MXKVStoreBarrier(KVStoreHandle handle);

/* -- data iterators (c_api_io.cc; reference c_api.h MXDataIter block).
 * Creator handles are interned iterator-name strings. GetData/GetLabel
 * return fresh handles onto the CURRENT batch (caller frees). */
typedef void *DataIterHandle;
typedef void *DataIterCreator;

int MXListDataIters(mx_uint *out_size, DataIterCreator **out_array);
int MXDataIterGetIterInfo(DataIterCreator creator, const char **name,
                          const char **description, mx_uint *num_args,
                          const char ***arg_names,
                          const char ***arg_type_infos,
                          const char ***arg_descriptions);
int MXDataIterCreateIter(DataIterCreator creator, mx_uint num_param,
                         const char **keys, const char **vals,
                         DataIterHandle *out);
int MXDataIterFree(DataIterHandle handle);
int MXDataIterNext(DataIterHandle handle, int *out);
int MXDataIterBeforeFirst(DataIterHandle handle);
int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out);
int MXDataIterGetPadNum(DataIterHandle handle, int *pad);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_API_H_ */
