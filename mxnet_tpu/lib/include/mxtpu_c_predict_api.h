/*
 * Flat C ABI for deployment inference (libmxtpu_capi.so).
 *
 * Mirrors the reference's include/mxnet/c_predict_api.h entry points
 * one-for-one; a host written against libmxnet's predict API recompiles
 * against this header unchanged. See mxnet_tpu/lib/src_capi/
 * c_predict_api.cc for semantics notes (MXPredPartialForward completes in
 * one step — the forward is a single fused XLA executable).
 */
#ifndef MXTPU_C_PREDICT_API_H_
#define MXTPU_C_PREDICT_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *PredictorHandle;
typedef void *NDListHandle;

const char *MXGetLastError();

int MXPredCreate(const char *symbol_json_str, const void *param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char **input_keys,
                 const mx_uint *input_shape_indptr,
                 const mx_uint *input_shape_data, PredictorHandle *out);

int MXPredCreatePartialOut(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id, mx_uint num_input_nodes,
                           const char **input_keys,
                           const mx_uint *input_shape_indptr,
                           const mx_uint *input_shape_data,
                           mx_uint num_output_nodes, const char **output_keys,
                           PredictorHandle *out);

int MXPredCreateMultiThread(const char *symbol_json_str,
                            const void *param_bytes, int param_size,
                            int dev_type, int dev_id, mx_uint num_input_nodes,
                            const char **input_keys,
                            const mx_uint *input_shape_indptr,
                            const mx_uint *input_shape_data, int num_threads,
                            PredictorHandle *out);

int MXPredReshape(mx_uint num_input_nodes, const char **input_keys,
                  const mx_uint *input_shape_indptr,
                  const mx_uint *input_shape_data, PredictorHandle handle,
                  PredictorHandle *out);

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint **shape_data, mx_uint *shape_ndim);

int MXPredSetInput(PredictorHandle handle, const char *key,
                   const mx_float *data, mx_uint size);

int MXPredForward(PredictorHandle handle);

int MXPredPartialForward(PredictorHandle handle, int step, int *step_left);

int MXPredGetOutput(PredictorHandle handle, mx_uint index, mx_float *data,
                    mx_uint size);

int MXPredFree(PredictorHandle handle);

int MXNDListCreate(const char *nd_file_bytes, int nd_file_size,
                   NDListHandle *out, mx_uint *out_length);

int MXNDListGet(NDListHandle handle, mx_uint index, const char **out_key,
                const mx_float **out_data, const mx_uint **out_shape,
                mx_uint *out_ndim);

int MXNDListFree(NDListHandle handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_PREDICT_API_H_ */
