"""Automatic FLOP accounting from JAX's lowered-HLO cost analysis.

MFU was the one telemetry number that still needed hand-feeding
(`set_step_flops` / `MXTPU_STEP_FLOPS`); every ROADMAP perf item stalls on
it. This module closes the loop: at jit-cache-fill time — the moment an
executable is built for a new (op, attrs, shapes) signature — the call
site asks XLA's HLO cost analysis how many FLOPs one execution costs
(`jax.stages.Lowered.cost_analysis()`, a trace+lower with NO backend
compile), remembers it, and every execution accumulates into a process-
wide counter. `observe_step` reads the per-step delta, so
`mxtpu_step_mfu` publishes with zero manual declarations, and the serving
layer prices each padding bucket (`mxtpu_serve_bucket_flops`) the same
way.

Accounting is wired at ONE place: the unified executable registry's fill
hook (`mxnet_tpu.compile.registry`), which every factory resolves
through — eager ops, autograd backward, Executor forward/backward,
gluon CachedOp, the sharded trainers, and via the Executor serving
bucket warm. Concrete fills price the executable once from the compile's
own `Lowered` (stored in persistent-tier artifact headers, so pricing
survives a zero-compile cold start); lazy fills use `instrument`'s
per-shape memo below. The cost: one extra trace+lower per NEW shape
signature (amortized to zero in steady state) and one float add per
execution. `MXTPU_TRACE_FLOPS=0` turns all of it off. Cost analysis can
fail (exotic primitives, missing backend support); every entry point
degrades to "unknown" (None) rather than ever breaking dispatch.

Jax is only imported lazily, from call sites that already did.
"""
from __future__ import annotations

from .. import env as _env
from . import core

__all__ = ["enabled", "accumulate", "total", "take_step_delta",
           "cost_analysis_flops", "measure", "PerShapeFlops"]


class _FlopState:
    def __init__(self):
        self.enabled = None     # None = read env lazily, cache after
        self.total = 0.0        # FLOPs executed since process start
        self.step_mark = 0.0    # total at the last observe_step
        self.last_step = None   # FLOPs attributed to the last step


_STATE = _FlopState()


def enabled():
    """Is automatic accounting on? (``MXTPU_TRACE_FLOPS``, default on;
    cached — flip it before the first compile, not mid-run.)"""
    if _STATE.enabled is None:
        _STATE.enabled = bool(core._STATE.enabled
                              and _env.get("MXTPU_TRACE_FLOPS"))
    return _STATE.enabled


def accumulate(flops):
    """Record one execution of an executable costing ``flops``. Plain
    float add — lock-free, same torn-sample trade as the metrics layer."""
    if flops:
        _STATE.total += flops


def total():
    """FLOPs executed by instrumented executables since process start.
    Serving warm brackets this to price each padding bucket."""
    return _STATE.total


def take_step_delta():
    """FLOPs executed since the previous call — the automatic per-step
    FLOP count `observe_step` uses when no manual value is declared.
    (Work between steps — eval forwards, serving traffic — lands in the
    next step's delta; steady-state training attributes cleanly.)"""
    t = _STATE.total
    delta = t - _STATE.step_mark
    _STATE.step_mark = t
    if delta > 0:
        _STATE.last_step = delta
    return delta


def last_step_flops():
    """The most recent nonzero per-step FLOP attribution (bench.py reports
    this next to its hand-computed number)."""
    return _STATE.last_step


def cost_analysis_flops(analysis):
    """Pull the ``flops`` figure out of a jax cost-analysis result, which
    is a dict in some jax versions and a per-computation list of dicts in
    others. Returns float or None."""
    if isinstance(analysis, (list, tuple)):
        vals = [d.get("flops") for d in analysis if isinstance(d, dict)]
        vals = [v for v in vals if v is not None and v >= 0]
        return float(sum(vals)) if vals else None
    if isinstance(analysis, dict):
        v = analysis.get("flops")
        return float(v) if v is not None and v >= 0 else None
    return None


def measure(jitted, args, kwargs=None):
    """FLOPs of one execution of ``jitted`` on ``args``: trace + lower
    (cheap; no backend compile) and run HLO cost analysis. None when
    accounting is off or analysis is unavailable for this computation."""
    if not enabled():
        return None
    try:
        lowered = jitted.lower(*args, **(kwargs or {}))
        return cost_analysis_flops(lowered.cost_analysis())
    except Exception:
        return None


def _shape_sig(x):
    """Hashable shape/dtype signature of a (possibly nested) argument."""
    if isinstance(x, (tuple, list)):
        return tuple(_shape_sig(e) for e in x)
    if isinstance(x, dict):
        return tuple(sorted((str(k), _shape_sig(v)) for k, v in x.items()))
    shape = getattr(x, "shape", None)
    if shape is None:
        return (type(x).__name__,)
    return (tuple(shape), str(getattr(x, "dtype", "")))


class PerShapeFlops:
    """Per-shape-signature FLOP memo for ONE jitted callable (whose jax-
    side cache is keyed by shapes the wrapper can't see). First call with
    a new signature pays one lower+cost-analysis; later calls are a dict
    lookup + float add."""

    __slots__ = ("_jitted", "_by_sig")

    def __init__(self, jitted):
        self._jitted = jitted
        self._by_sig = {}

    def observe(self, args):
        sig = _shape_sig(args)
        flops = self._by_sig.get(sig, -1.0)
        if flops == -1.0:
            flops = measure(self._jitted, args)
            self._by_sig[sig] = flops
        if flops:
            _STATE.total += flops


def instrument(jitted):
    """Wrap a jitted callable so every execution feeds the accumulator
    (per-shape memo as above). Returns ``jitted`` unchanged when
    accounting is off — zero overhead."""
    if not enabled():
        return jitted
    memo = PerShapeFlops(jitted)

    def call(*args):
        memo.observe(args)
        return jitted(*args)

    call._flops_memo = memo  # introspection for tests
    return call
