"""Memory observability: HBM attribution, live accounting, OOM forensics.

The reference framework devoted a whole layer to memory (the storage
allocator + NNVM memory planning, PAPER.md) and shipped a graph memory
profiler; on TPUs HBM — not FLOPs — is the resource that gates replica
density, donated whole-step buffers and prefetch depth. This module is
the third axis of the telemetry spine (time = tracing, compute = flops,
memory = here), in three parts:

  * **per-executable attribution** — at the unified executable registry's
    single fill hook (`mxnet_tpu.compile.registry`, exactly where FLOP
    pricing lives), every AOT compile captures
    `Compiled.memory_analysis()`: argument / output / temp / generated-
    code / aliased bytes. The figures are recorded in a process-wide
    table (`record_executable`), persisted in the ``MXTPUEXE1`` artifact
    header, and read back on a persistent-tier hit — a zero-compile cold
    start still knows every executable's footprint. The serving layer
    brackets its per-bucket warm with `recorded_mark`/`recorded_since`
    to price each padding bucket (`model_footprint`), which is what the
    ``MXTPU_SERVE_MEMORY_BUDGET`` admission check enforces.
  * **live accounting** — device gauges polled from jax
    ``memory_stats()`` (graceful None on CPU), process RSS/VmHWM from
    ``/proc/self/status`` (real numbers even where the backend reports
    nothing), NDArray live-count/live-bytes maintained at construction /
    ``__del__`` (ndarray.py hooks), and a per-step peak-delta histogram
    (`observe_step_delta`) so a trace exemplar can name the step that
    spiked.
  * **forensics** — `snapshot()` is the flight recorder's memory block:
    gauge values, the last polled device stats, and the top-N
    executables by temp bytes. It is SIGNAL-SAFE by construction (plain
    dict reads, one /proc file read, no jax, no locks, no logging) and
    is walked by mxlint's signal-safety checker. The **donation
    verifier** (`verify_donation`, called from the fill hook for keys
    that declare donated arguments) checks from memory_analysis that the
    fused trainer step actually aliases its donated param/optimizer
    buffers — ROADMAP item 1's key invariant as a checked metric
    (`mxtpu_donation_alias_bytes` vs `mxtpu_donation_declared_bytes`)
    instead of a hope.

Pure stdlib on every always-on path; jax is touched only from
`sample_devices` (never from the signal path — the dump reads the cached
last sample). ``MXTPU_TELEMETRY=0`` turns everything into no-ops.
"""
from __future__ import annotations

import collections
import os
import sys
import threading
import time

try:  # imported at module load, NOT from the signal path (import lock)
    import resource as _resource
except ImportError:  # non-POSIX
    _resource = None

from .. import env as _env
from . import core

__all__ = [
    "enabled", "from_compiled", "record_executable", "lookup_key",
    "recorded_mark", "recorded_since", "executables_top", "sum_figures",
    "bucket_figures", "footprint_bytes", "verify_donation",
    "last_donation_report", "read_process_memory", "sample_devices",
    "sample", "observe_step_delta", "snapshot", "ndarray_created",
    "ndarray_freed", "ndarray_resized", "ndarray_live", "parse_bytes",
    "serve_memory_budget", "model_footprint", "ensure_poller",
]

# memory_analysis attribute -> short figure key (the artifact-header and
# snapshot spelling; host_* variants are ignored — device memory is the
# scarce resource this module exists for)
_FIGURES = (
    ("argument_size_in_bytes", "arguments"),
    ("output_size_in_bytes", "outputs"),
    ("temp_size_in_bytes", "temp"),
    ("generated_code_size_in_bytes", "generated_code"),
    ("alias_size_in_bytes", "alias"),
)


def enabled():
    """Memory accounting rides the master telemetry switch — there is no
    separate gate: every always-on path is a handful of plain adds."""
    return core._STATE.enabled


# ---------------------------------------------------------------------------
# per-executable attribution (fed by mxnet_tpu.compile.registry)
# ---------------------------------------------------------------------------

class _MemState:
    def __init__(self):
        # executable table: insertion-ordered digest/label -> figures
        # (plain dict: GIL-atomic reads keep snapshot() signal-safe)
        self.executables = {}
        # PER-THREAD attribution log (same discipline as the registry's
        # per-thread fill log): a warm brackets its own thread's records
        # with recorded_mark/_since, so a concurrent load or live batcher
        # traffic on another thread never inflates a bucket's figures —
        # and each thread's log is a BOUNDED deque, so a long-lived
        # serving worker can't leak through its own telemetry
        self.log_local = threading.local()
        self.nd_live = [0, 0]    # [count, bytes] — ndarray.py hooks
        self.devices = None      # last sample_devices() result (cached
        #                          for the signal-safe snapshot)
        self.devices_ts = None
        self.caps = None         # does the backend report memory_stats?
        self.step_peak = None    # peak bytes at the last observe_step
        self.step_peak_ts = 0.0  # monotonic time of that probe
        self.last_donation = None
        self.poller = None
        self.poller_decided = False


_STATE = _MemState()
_MAX_EXECUTABLES = 4096  # runaway-shape backstop, same order as the LRU
_MAX_LOG = 4096          # per-thread attribution-log bound
# serializes ensure_poller's cold path only (same double-checked shape as
# core._DECIDE_LOCK): an unlocked decided-flag race could start 2 pollers
_DECIDE_LOCK = threading.Lock()


def _reset_after_fork():
    st = _MemState()
    st.executables = dict(_STATE.executables)  # attribution is still true
    # inherited NDArrays are alive in the child and their __del__ will
    # decrement — the counts must carry over or the gauges go negative
    st.nd_live = list(_STATE.nd_live)
    globals()["_STATE"] = st


def _thread_log():
    """(seq_counter_ref, entries deque) for the calling thread. Entries
    are (seq, entry_key) pairs; the deque bound means a cursor older than
    the window simply sees fewer entries, never wrong ones."""
    local = _STATE.log_local
    entries = getattr(local, "entries", None)
    if entries is None:
        entries = local.entries = collections.deque(maxlen=_MAX_LOG)
        local.seq = 0
    return local, entries


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_after_fork)


def from_compiled(compiled):
    """Figures dict from a jax ``Compiled``'s ``memory_analysis()``, or
    None when the backend doesn't support it (never raises — attribution
    is best-effort, exactly like FLOP pricing)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for attr, name in _FIGURES:
        v = getattr(ma, attr, None)
        if v is None and isinstance(ma, dict):
            v = ma.get(attr)
        if v is not None:
            out[name] = int(v)
    return out or None


def record_executable(kind, label, digest, figures, key=None):
    """Record one executable's memory figures into the process table (and
    the bracketing log). ``key`` (the registry's `ExecutableKey`) indexes
    the entry so later MEMORY-TIER HITS can still be attributed — a
    reload of an already-resident model fills nothing, but its warm still
    touches the keys (`lookup_key`). Safe with figures=None (no-op)."""
    if not figures or not enabled():
        return
    local, entries = _thread_log()
    entry_key = key if key is not None else (
        digest or "%s:%s:%d" % (kind, label, local.seq))
    entry = {"kind": kind, "label": label, "digest": digest}
    entry.update(figures)
    if len(_STATE.executables) >= _MAX_EXECUTABLES \
            and entry_key not in _STATE.executables:
        _STATE.executables.pop(next(iter(_STATE.executables)), None)
    _STATE.executables[entry_key] = entry
    local.seq += 1
    entries.append((local.seq, entry_key))


def lookup_key(key):
    """Figures entry recorded under a registry `ExecutableKey`, or None."""
    return _STATE.executables.get(key)


def recorded_mark():
    """Cursor into THIS THREAD's attribution log — bracket a load/warm
    with `recorded_mark()` / `recorded_since()` to learn which
    executables' figures it contributed (the serving per-bucket
    footprint). Fills on other threads never leak into the bracket."""
    local, _ = _thread_log()
    return local.seq


def recorded_since(cursor):
    """This thread's figure entries recorded since ``cursor``
    (deduplicated, in fill order)."""
    _, entries = _thread_log()
    seen, out = set(), []
    for seq, k in entries:
        if seq <= cursor or k in seen:
            continue
        seen.add(k)
        entry = _STATE.executables.get(k)
        if entry is not None:
            out.append(entry)
    return out


def executables_top(n=10, by="temp"):
    """Top-``n`` recorded executables by one figure (default temp bytes —
    the live-working-set contribution). Plain dict reads: signal-safe."""
    rows = [e for e in list(_STATE.executables.values()) if e.get(by)]
    rows.sort(key=lambda e: e.get(by, 0), reverse=True)
    return rows[:n]


def sum_figures(entries):
    """Combine several executables' figure dicts into one (the serving
    per-bucket roll-up: a bucket warm may fill forward + helper
    executables). {} when nothing was recorded."""
    out = {}
    for entry in entries:
        for _, name in _FIGURES:
            v = entry.get(name)
            if v is not None:
                out[name] = out.get(name, 0) + int(v)
    return out


def bucket_figures(touched_keys, recorded_entries):
    """One bucket warm's combined figures: the entries its FILLS recorded
    (`recorded_since`) plus table entries for the keys it merely TOUCHED
    (memory-tier hits on an already-resident executable — the reload
    path), each executable counted once."""
    seen, entries = set(), []
    for e in recorded_entries:
        if id(e) not in seen:
            seen.add(id(e))
            entries.append(e)
    for k in touched_keys:
        e = _STATE.executables.get(k)
        if e is not None and id(e) not in seen:
            seen.add(id(e))
            entries.append(e)
    return sum_figures(entries)


def footprint_bytes(figures):
    """One executable's device-footprint contribution: arguments +
    outputs + temps + generated code, minus aliased (donated) bytes that
    arguments and outputs double-count."""
    if not figures:
        return 0
    return max(0, figures.get("arguments", 0) + figures.get("outputs", 0)
               + figures.get("temp", 0) + figures.get("generated_code", 0)
               - figures.get("alias", 0))


def model_footprint(per_bucket):
    """Total footprint of a served model from its per-bucket figures
    (``{bucket: figures}``). Buckets SHARE weights (the argument bytes
    are dominated by one weight copy per model, `predict._clone_with`),
    so the total counts the largest bucket's argument bytes once plus
    every bucket's private outputs/temps/code."""
    if not per_bucket:
        return None
    args = max((f.get("arguments", 0) for f in per_bucket.values()),
               default=0)
    private = sum(f.get("outputs", 0) + f.get("temp", 0)
                  + f.get("generated_code", 0)
                  for f in per_bucket.values())
    return args + private


# ---------------------------------------------------------------------------
# donation verifier
# ---------------------------------------------------------------------------

def _leaf_nbytes(x):
    nb = getattr(x, "nbytes", None)
    if nb is not None:
        return int(nb)
    if isinstance(x, (list, tuple)):
        return sum(_leaf_nbytes(e) for e in x)
    if isinstance(x, dict):
        return sum(_leaf_nbytes(v) for v in x.values())
    # aval-only example args (jax.ShapeDtypeStruct): size from shape/dtype
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        n = 1
        for d in shape:
            n *= int(d)
        return n * int(getattr(dtype, "itemsize", 0) or 0)
    return 0


def verify_donation(key, example_args, figures, threshold=0.5):
    """Check, from an executable's memory figures, that the buffers its
    key DECLARES donated (``key.donation`` argnums) were actually aliased
    by XLA (``alias`` bytes ≈ donated bytes). Publishes
    ``mxtpu_donation_declared_bytes`` / ``mxtpu_donation_alias_bytes``
    gauges (labeled by key kind) and a ``donation_unaliased`` flight-
    recorder event when the aliased fraction falls under ``threshold`` —
    a fused trainer step that silently stopped donating is an extra
    whole-model allocation, exactly the regression ROADMAP item 1 cannot
    afford. Returns the report dict (also kept for
    `last_donation_report`), or None when unverifiable."""
    if not enabled() or not key.donation or figures is None \
            or figures.get("alias") is None:
        return None
    declared = 0
    for i in key.donation:
        try:
            declared += _leaf_nbytes(example_args[int(i)])
        except (IndexError, TypeError, ValueError):
            return None
    if not declared:
        return None
    alias = int(figures.get("alias", 0))
    report = {
        "kind": key.kind,
        "declared_bytes": int(declared),
        "alias_bytes": alias,
        "aliased_fraction": alias / float(declared),
        "ok": alias >= threshold * declared,
    }
    _STATE.last_donation = report
    labels = {"kind": key.kind}
    core.gauge("mxtpu_donation_declared_bytes", labels).set(declared)
    core.gauge("mxtpu_donation_alias_bytes", labels).set(alias)
    if not report["ok"]:
        from . import recorder

        recorder.record_event(
            "donation_unaliased", key_kind=key.kind,
            declared_bytes=int(declared), alias_bytes=alias,
            aliased_fraction=round(report["aliased_fraction"], 4))
    return report


def last_donation_report():
    """The most recent `verify_donation` report (bench evidence reads
    this after one trainer step), or None."""
    return _STATE.last_donation


# ---------------------------------------------------------------------------
# live accounting: process / device / NDArray
# ---------------------------------------------------------------------------

def read_process_memory():
    """{'rss': bytes, 'vmhwm': bytes} from ``/proc/self/status`` (stdlib,
    ~50µs), or None off-Linux. Kernels that hide ``VmHWM`` (sandboxed
    containers) fall back to ``getrusage`` ru_maxrss for the high-water
    mark. Works where ``memory_stats()`` returns None — CPU boxes get
    real numbers. Signal-safe: one file read + one syscall."""
    out = {}
    try:
        with open("/proc/self/status") as f:
            text = f.read()
    except OSError:
        text = ""
    for line in text.splitlines():
        if line.startswith("VmRSS:"):
            out["rss"] = int(line.split()[1]) * 1024
        elif line.startswith("VmHWM:"):
            out["vmhwm"] = int(line.split()[1]) * 1024
    if "vmhwm" not in out and _resource is not None:
        try:
            out["vmhwm"] = _resource.getrusage(
                _resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            pass
    return out or None


def sample_devices():
    """Poll ``memory_stats()`` on every local device into per-device
    dicts (bytes_in_use / peak_bytes_in_use / bytes_limit, whichever the
    backend reports). Returns None on backends without stats (CPU) —
    gracefully, once (the capability is cached). NEVER called from the
    signal path (the dump reads the cached last sample), and NEVER the
    first thing to touch the backend: a telemetry flusher/scrape thread
    must not initialize XLA — or block on a wedged accelerator dial, the
    failure class `runtime.dial_devices` bounds — so sampling waits
    until some real computation has already brought the backend up."""
    if _STATE.caps is False or not enabled():
        return _STATE.devices if _STATE.caps else None
    if "jax" not in sys.modules:
        return None
    try:
        import jax
        from jax._src import xla_bridge as _xb

        if not getattr(_xb, "_backends", None):
            return None  # backend not initialized — do not dial from here
        devs = jax.local_devices()
    except Exception:
        return None
    out = {}
    for d in devs:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        out[str(getattr(d, "id", len(out)))] = {
            k: int(v) for k, v in stats.items()
            if isinstance(v, (int, float)) and k in (
                "bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_free_block_bytes", "bytes_reserved")}
    # last-sample cache, lock-free BY DESIGN: the flight recorder's
    # signal-context snapshot() reads these fields, so no lock may ever
    # guard them (poller/flusher/scrape racers each publish a complete
    # sample; a reader sees one sample or the other, never a crash)
    if not out:
        _STATE.caps = False  # mxlint: gil-atomic — signal-safe cache
        return None
    _STATE.caps = True  # mxlint: gil-atomic — signal-safe cache
    _STATE.devices = out  # mxlint: gil-atomic — signal-safe cache
    _STATE.devices_ts = time.time()  # mxlint: gil-atomic — signal-safe cache
    for dev_id, stats in out.items():
        labels = {"device": dev_id}
        if "bytes_in_use" in stats:
            core.gauge("mxtpu_device_bytes_in_use", labels).set(
                stats["bytes_in_use"])
        if "peak_bytes_in_use" in stats:
            core.gauge("mxtpu_device_bytes_peak", labels).set(
                stats["peak_bytes_in_use"])
        if "bytes_limit" in stats:
            core.gauge("mxtpu_device_bytes_limit", labels).set(
                stats["bytes_limit"])
    return out


def ndarray_created(nbytes):
    """NDArray construction hook (ndarray.py): plain list adds — this is
    the imperative hot path."""
    st = _STATE.nd_live
    st[0] += 1
    st[1] += nbytes


def ndarray_freed(nbytes):
    """NDArray ``__del__`` hook. Must never raise: interpreter shutdown
    may have torn half the module down already."""
    try:
        st = _STATE.nd_live
        st[0] -= 1
        st[1] -= nbytes
    except Exception:
        pass


def ndarray_resized(delta):
    """`_set_data` swapped in a different-sized buffer."""
    _STATE.nd_live[1] += delta


def ndarray_live():
    """(live_count, live_bytes) of NDArray handles this process holds."""
    return _STATE.nd_live[0], _STATE.nd_live[1]


def sample(devices=True):
    """Refresh every memory gauge: process RSS/VmHWM, NDArray live
    count/bytes, and (``devices=True``) the per-device stats. Called from
    the JSONL flush, the Prometheus scrape, the optional poller thread
    (``MXTPU_MEMORY_POLL_MS``) and per-step. Cheap: one /proc read plus
    plain gauge stores."""
    if not enabled():
        return None
    proc = read_process_memory()
    if proc is not None:
        if "rss" in proc:
            core.gauge("mxtpu_process_rss_bytes").set(proc["rss"])
        if "vmhwm" in proc:
            core.gauge("mxtpu_process_vmhwm_bytes").set(proc["vmhwm"])
    live, live_bytes = ndarray_live()
    core.gauge("mxtpu_ndarray_live").set(live)
    core.gauge("mxtpu_ndarray_live_bytes").set(live_bytes)
    if devices:
        sample_devices()
    return proc


def _peak_bytes():
    """The process's best peak-memory signal: device peak when the
    backend reports one (HBM is what OOMs), else the RSS high-water
    mark. This sits on the per-step hot path, so the host fallback is
    ONE getrusage syscall — never a /proc read (~200µs on sandboxed
    kernels, which a <2%-overhead budget cannot afford)."""
    if _STATE.caps is not False:
        devs = sample_devices()
        if devs:
            return sum(s.get("peak_bytes_in_use", 0) for s in devs.values())
    if _resource is not None:
        try:
            return _resource.getrusage(_resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            pass
    proc = read_process_memory()
    if proc is None:
        return None
    return proc.get("vmhwm") or proc.get("rss")


_STEP_PROBE_MIN_S = 0.1  # rate limit: the peak probe is a syscall (and
#                          sandboxed kernels make getrusage ~15µs); steps
#                          faster than this share one probe window — the
#                          <2% per-step overhead contract stands, and
#                          fast steps barely move the peak anyway


def observe_step_delta(exemplar=None, force=False):
    """Per-step peak-memory growth: how much the peak (device, else
    VmHWM) moved since the previous probe, into the
    ``mxtpu_step_peak_bytes_delta`` histogram — with the step's trace id
    as exemplar, so the step that spiked memory names a renderable
    trace. Called from `telemetry.observe_step`; probed at most every
    ``_STEP_PROBE_MIN_S`` (``force=True`` bypasses — tests)."""
    if not enabled():
        return
    now = time.monotonic()
    if not force and now - _STATE.step_peak_ts < _STEP_PROBE_MIN_S:
        return
    _STATE.step_peak_ts = now
    peak = _peak_bytes()
    if peak is None:
        return
    prev = _STATE.step_peak
    _STATE.step_peak = peak
    if prev is None:
        return
    core.histogram("mxtpu_step_peak_bytes_delta",
                   bounds=core.BYTE_BOUNDS).observe(
        max(0, peak - prev), exemplar=exemplar)


# ---------------------------------------------------------------------------
# poller
# ---------------------------------------------------------------------------

def _poller_loop(period_s):
    while True:
        time.sleep(period_s)
        if os.getpid() != core._STATE.owner_pid:
            return
        sample()


def ensure_poller():
    """Start the background gauge poller once if ``MXTPU_MEMORY_POLL_MS``
    asks for one (default off — the flush/scrape/step sampling is enough
    for most runs; long forwards between steps are what the poller is
    for). Env decision cached, same discipline as the flusher."""
    if _STATE.poller_decided:
        return
    with _DECIDE_LOCK:  # double-checked: only the cold path locks
        if _STATE.poller_decided:
            return
        _STATE.poller_decided = True
        if not enabled():
            return
        period_ms = _env.get("MXTPU_MEMORY_POLL_MS")
        if not period_ms or period_ms <= 0:
            return
        t = threading.Thread(target=_poller_loop,
                             args=(max(0.01, period_ms / 1e3),),
                             name="mxtpu-memory-poll", daemon=True)
        _STATE.poller = t
        t.start()


# ---------------------------------------------------------------------------
# forensics snapshot (flight-recorder dump block — SIGNAL-SAFE)
# ---------------------------------------------------------------------------

def snapshot(top_n=10):
    """The flight recorder's memory block: process RSS/VmHWM (read fresh
    — one /proc read), the LAST polled device stats (never a fresh jax
    call from a signal context), NDArray live accounting, the top-N
    executables by temp bytes, and the last donation report. Every hang/
    OOM dump says what was resident. Walked by mxlint signal-safety."""
    return {
        "process": read_process_memory(),
        "devices": _STATE.devices,
        "devices_sampled_ago_s":
            None if _STATE.devices_ts is None
            else round(time.time() - _STATE.devices_ts, 1),
        "ndarray": {"live": _STATE.nd_live[0],
                    "live_bytes": _STATE.nd_live[1]},
        "executables_by_temp": executables_top(top_n),
        "donation": _STATE.last_donation,
    }


# ---------------------------------------------------------------------------
# serving memory budget
# ---------------------------------------------------------------------------

_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_bytes(text):
    """``"1073741824"`` / ``"512M"`` / ``"1.5G"`` -> bytes (int), or None
    on a value that parses to nothing."""
    s = str(text).strip().lower()
    if not s:
        return None
    mult = 1
    if s[-1] in _SUFFIX:
        mult = _SUFFIX[s[-1]]
        s = s[:-1]
    try:
        return int(float(s) * mult)
    except ValueError:
        return None


def serve_memory_budget():
    """The serving memory budget from ``MXTPU_SERVE_MEMORY_BUDGET``:
    ``(limit_bytes, warn_only)`` or ``(None, False)`` when unset. A
    ``warn:`` prefix turns rejection into a logged warning (canary
    posture); a malformed value disables the check (never blocks a
    load)."""
    raw = _env.raw("MXTPU_SERVE_MEMORY_BUDGET") or ""
    warn = False
    if raw.lower().startswith("warn:"):
        warn = True
        raw = raw[5:]
    limit = parse_bytes(raw) if raw else None
    return limit, warn
