"""Training goodput accounting: per-step stall attribution and cumulative
phase totals (docs/observability.md §Goodput).

Every training step — gluon ``Trainer.step``, ``DistributedTrainer``/
``ShardedTrainer``/``PipelineTrainer.step``, ``module.fit`` — brackets
itself with :func:`step_start` / :func:`step_end` and attributes slices of
its wall time to exhaustive, non-overlapping phases:

``data_wait``
    iterator ``next()`` / ``device_put`` / batch-shard blocking
``host_dispatch``
    Python between step entry and the executable launch
    (:func:`mark_launch`) that no finer phase claimed
``compile``
    executable-cache miss time (``compile.registry`` attributes its whole
    miss path — persistent-tier loads and true fills)
``compute``
    the device step itself
``checkpoint_stall``
    sync save + async-writer submit blocking
    (``parallel.resilience`` forwards its ``mxtpu_checkpoint_stall_seconds``
    observations here)
``collective``
    gradient allreduce outside the fused step
``other``
    the honest remainder — ``wall - sum(attributed)``, never negative

Per-step phases land in ``mxtpu_step_phase_seconds{phase=}`` histograms
(with trace-id exemplars when the step's root span is sampled) and
cumulative ``mxtpu_goodput_phase_seconds_total{phase=}`` counters; a
rolling window of the last ``MXTPU_GOODPUT_WINDOW_STEPS`` steps feeds the
``mxtpu_goodput_fraction`` gauge (windowed compute ÷ wall) and the
``/statusz`` ``training`` block. Time between steps (the training loop
doing neither) accumulates in the cumulative-only ``between_steps``
phase — it has no per-step histogram because it is not part of any step —
minus whatever out-of-step attribution (e.g. a checkpoint stall between
steps) already claimed. ``tools/goodput_report.py`` joins these counters
from each rank's final telemetry flush with the launcher's
``launcher-events.jsonl`` generation/downtime ledger into the whole-job
decomposition.

Accounting state is thread-local: concurrent trainers (tests, serving +
training in one process) never cross-attribute. All read paths used by
signal handlers (:func:`snapshot`, :func:`statusz_block`) are lock-free
and allocation-light — mxlint's signal-safety checker walks them.
"""
import atexit
import collections
import threading
import time

from .. import env as _env
from . import core as _core
from . import tracing as _tracing

# step-internal phases (each has a per-step histogram series);
# ``between_steps`` additionally exists as a cumulative-only counter label
PHASES = ("data_wait", "host_dispatch", "compile", "compute",
          "checkpoint_stall", "collective", "other")

_TLS = threading.local()

# rolling (wall, compute, stall_phase, stall_seconds) of recent steps —
# sized lazily from MXTPU_GOODPUT_WINDOW_STEPS at first step
_WINDOW = collections.deque(maxlen=128)
_WINDOW_SIZED = False

_FIRST_STEP_TS = None  # wall-clock ts of the first completed step
_PROC_T0 = time.time()  # module import ≈ process start (post-fork exec)

_METRICS = None  # (hist_by_phase, ctr_by_phase, wall_ctr, frac_gauge)

_ATEXIT_REGISTERED = False


def _enabled():
    return _core._STATE.enabled and _env.get("MXTPU_GOODPUT")


def _metrics():
    global _METRICS, _WINDOW_SIZED, _WINDOW
    m = _METRICS
    if m is None:
        hists = {p: _core.histogram("mxtpu_step_phase_seconds",
                                    {"phase": p}) for p in PHASES}
        ctrs = {p: _core.counter("mxtpu_goodput_phase_seconds_total",
                                 {"phase": p})
                for p in PHASES + ("between_steps",)}
        m = _METRICS = (hists, ctrs,
                        _core.counter("mxtpu_goodput_wall_seconds_total"),
                        _core.gauge("mxtpu_goodput_fraction"))
    if not _WINDOW_SIZED:
        n = max(8, int(_env.get("MXTPU_GOODPUT_WINDOW_STEPS")))
        if n != _WINDOW.maxlen:
            _WINDOW = collections.deque(_WINDOW, maxlen=n)
        _WINDOW_SIZED = True  # mxlint: gil-atomic — one-time sizing latch
    return m


def _acct():
    return getattr(_TLS, "acct", None)


def step_start(kind="train", t0=None):
    """Open a step accounting bracket. ``t0`` back-dates the step start
    (``module.fit`` opens the bracket only after a successful iterator
    ``next()`` so StopIteration leaves no dangling bracket, but the wait
    itself belongs to the step). A bracket left open by a step that
    raised is silently discarded — no trainer nests one step inside
    another, so an open bracket here can only be stale."""
    if not _enabled():
        return
    now = time.perf_counter()
    t0 = now if t0 is None else t0
    # idle time since the previous step's end that no out-of-step add()
    # claimed: the training loop doing neither compute nor a named stall
    last_end = getattr(_TLS, "last_end", None)
    if last_end is not None and t0 > last_end:
        claimed = getattr(_TLS, "gap_attr", 0.0)
        gap = max(0.0, (t0 - last_end) - claimed)
        if gap > 0.0:
            _metrics()[1]["between_steps"].inc(gap)
    _TLS.gap_attr = 0.0
    _TLS.acct = {"kind": kind, "t0": t0, "phases": {}, "launched": False}
    global _ATEXIT_REGISTERED
    if not _ATEXIT_REGISTERED:
        _ATEXIT_REGISTERED = True  # mxlint: gil-atomic — one-time latch
        # Registered at first step (AFTER core registered its final flush),
        # so LIFO atexit publishes the abandoned bracket before the flush.
        atexit.register(finalize)


def add(phase, seconds):
    """Attribute ``seconds`` to ``phase``. Inside an open bracket the time
    joins the current step; outside (async checkpoint submit between
    steps, compile at trainer construction) it goes straight to the
    cumulative counter and reduces the next ``between_steps`` gap."""
    if seconds <= 0.0 or phase not in PHASES or not _enabled():
        return
    a = _acct()
    if a is not None:
        ph = a["phases"]
        ph[phase] = ph.get(phase, 0.0) + seconds
        return
    _metrics()[1][phase].inc(seconds)
    _TLS.gap_attr = getattr(_TLS, "gap_attr", 0.0) + seconds


class phase:
    """``with goodput.phase("compute"):`` — attribute the block's elapsed
    time, MINUS whatever finer-grained attribution happened inside the
    block (an op resolving through the compile registry mid-step adds
    ``compile`` seconds; they must not also count as ``compute``). Keeps
    phases non-overlapping by construction. Cheap no-op when disabled."""

    __slots__ = ("_name", "_t0", "_nested0")

    def __init__(self, name):
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        a = _acct()
        self._nested0 = sum(a["phases"].values()) if a is not None else None
        return self

    def __exit__(self, *exc):
        elapsed = time.perf_counter() - self._t0
        a = _acct()
        if a is not None and self._nested0 is not None:
            elapsed -= sum(a["phases"].values()) - self._nested0
        add(self._name, elapsed)
        return False


def mark_launch():
    """Stamp the executable-launch point: everything since step start that
    no finer phase claimed becomes ``host_dispatch`` (argument wrapping,
    cache lookups, Python glue before the device gets work)."""
    a = _acct()
    if a is None or a["launched"]:
        return
    a["launched"] = True
    elapsed = time.perf_counter() - a["t0"]
    ph = a["phases"]
    add("host_dispatch", elapsed - sum(ph.values()))


def step_end(step=None, examples=None):
    """Close the bracket: fill ``other`` with the unattributed remainder,
    publish per-phase histograms (exemplar = the step's sampled trace id,
    if any) + cumulative counters, advance the rolling window and the
    ``mxtpu_goodput_fraction`` gauge. Returns the step's phase dict
    (plus ``wall``) — tests assert exhaustiveness on it."""
    a = _acct()
    if a is None:
        return None
    _TLS.acct = None
    now = time.perf_counter()
    _TLS.last_end = now
    wall = max(0.0, now - a["t0"])
    ph = a["phases"]
    attributed = sum(ph.values())
    if attributed < wall:
        ph["other"] = ph.get("other", 0.0) + (wall - attributed)
    hists, ctrs, wall_ctr, frac = _metrics()
    tid = _tracing.current_trace_id()
    for p, v in ph.items():
        if v > 0.0:
            hists[p].observe(v, exemplar=tid)
            ctrs[p].inc(v)
    wall_ctr.inc(wall)

    compute = ph.get("compute", 0.0)
    stall_phase, stall_s = None, 0.0
    for p, v in ph.items():
        if p != "compute" and v > stall_s:
            stall_phase, stall_s = p, v
    _WINDOW.append((wall, compute, stall_phase, stall_s))
    w_wall = w_compute = 0.0
    for e in _win_steps():
        w_wall += e[0]
        w_compute += e[1]
    if w_wall > 0.0:
        frac.set(w_compute / w_wall)

    global _FIRST_STEP_TS
    if _FIRST_STEP_TS is None:
        _FIRST_STEP_TS = time.time()  # mxlint: gil-atomic — one-time stamp
        # the launcher ledger joins this against generation start to price
        # restart cost (rendezvous + restore + first-step compile).
        # ``startup_s`` runs module import → first step START (the step
        # itself is already phase-attributed — no double counting);
        # ``step_wall_s`` lets tools/goodput_report.py anchor the
        # attributed window's wall-clock start at ``ts - step_wall_s``.
        # Lazy import: recorder imports goodput for dumps, not the reverse.
        from . import recorder as _recorder

        _recorder.record_event(
            "goodput_first_step", trainer=a["kind"],
            generation=_core.restart_generation(),
            startup_s=round(max(0.0, _FIRST_STEP_TS - wall - _PROC_T0), 3),
            step_wall_s=round(wall, 4))
    out = dict(ph)
    out["wall"] = wall
    return out


def finalize():
    """Salvage an abandoned step bracket at process exit: a SIGTERM mid-
    step unwinds through ``phase.__exit__`` (so e.g. the seconds blocked
    in a dead peer's allreduce DID land in the bracket's ``collective``
    slot) but never reaches :func:`step_end`. Publish those accumulated
    phases to the cumulative counters so the rank's final telemetry flush
    carries them — registered at the first :func:`step_start` so LIFO
    atexit runs it before core's final flush. Reads the CALLING thread's
    bracket (atexit → main thread, where training loops run); a bracket
    open on another thread at exit is lost, which only widens the
    report's honest ``shutdown`` remainder."""
    a = _acct()
    if a is None or not _enabled():
        return
    _TLS.acct = None
    ph = a["phases"]
    attributed = sum(ph.values())
    if attributed <= 0.0:
        return
    _, ctrs, wall_ctr, _ = _metrics()
    for p, v in ph.items():
        if v > 0.0:
            ctrs[p].inc(v)
    # wall advances only by what was attributed: the tail between the
    # last phase exit and interpreter death is exit handling, not step
    # time — the report prices it from launcher timestamps instead.
    wall_ctr.inc(attributed)


def _win_steps():
    """Stable copy of the rolling step window (same retry discipline as
    core._win_entries — a trainer thread appending during a signal-context
    read raises RuntimeError)."""
    for _ in range(4):
        try:
            return list(_WINDOW)
        except RuntimeError:
            continue
    return []


def totals():
    """Cumulative attributed seconds per phase (including
    ``between_steps``) + total step wall. Plain value reads —
    signal-safe."""
    m = _METRICS
    if m is None:
        return {"phases": {}, "wall": 0.0}
    return {"phases": {p: c._value for p, c in m[1].items() if c._value},
            "wall": m[2]._value}


def statusz_block():
    """The `/statusz` ``training`` block: windowed goodput fraction, top
    stall phase over the window, cumulative totals, startup cost."""
    entries = _win_steps()
    w_wall = sum(e[0] for e in entries)
    w_compute = sum(e[1] for e in entries)
    stalls = {}
    for e in entries:
        if e[2] is not None:
            stalls[e[2]] = stalls.get(e[2], 0.0) + e[3]
    top = max(stalls.items(), key=lambda kv: kv[1]) if stalls else None
    block = {
        "enabled": bool(_enabled()),
        "window_steps": len(entries),
        "goodput_fraction": round(w_compute / w_wall, 4) if w_wall else None,
        "top_stall_phase": top[0] if top else None,
        "top_stall_seconds": round(top[1], 4) if top else 0.0,
        "totals": totals(),
    }
    if _FIRST_STEP_TS is not None:
        block["first_step_startup_s"] = round(_FIRST_STEP_TS - _PROC_T0, 3)
    return block


def snapshot():
    """Flight-recorder dump payload: statusz block shape (signal-safe)."""
    return statusz_block()


def _reset_for_tests():
    global _WINDOW, _WINDOW_SIZED, _METRICS, _FIRST_STEP_TS
    _WINDOW = collections.deque(maxlen=128)
    _WINDOW_SIZED = False
    _METRICS = None
    _FIRST_STEP_TS = None
    _TLS.acct = None
    _TLS.last_end = None
    _TLS.gap_attr = 0.0
