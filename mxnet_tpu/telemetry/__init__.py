"""mxnet_tpu.telemetry — always-on runtime metrics + distributed flight
recorder.

One coherent telemetry spine for the framework (docs/observability.md):

  * `counter` / `gauge` / `histogram` — lock-free per-process metrics with
    periodic JSONL flush (``MXTPU_TELEMETRY_DIR``) and an optional
    Prometheus text endpoint (``MXTPU_TELEMETRY_PORT``) — core.py;
  * `record_event` / `record_step` / `dump` — a ring buffer of recent
    events plus a hang watchdog (``MXTPU_WATCHDOG_TIMEOUT``) and SIGUSR1
    stack dumps — recorder.py;
  * `observe_step` — the single call every trainer step makes: step wall
    time, examples/sec, achieved MFU (when per-step FLOPs are known), and
    the watchdog heartbeat.

Zero hard dependencies (pure stdlib; jax is only touched lazily for the MFU
peak-FLOPs lookup), metrics default ON, exporters default OFF.
"""
from __future__ import annotations


from .. import env as _env
from .core import (  # noqa: F401
    BYTE_BOUNDS, LATENCY_BOUNDS, counter, enabled, flush, gauge,
    get_registry, histogram, prometheus_text, rank, restart_generation,
    set_enabled, snapshot, start_http_server, telemetry_dir,
)
from .recorder import (  # noqa: F401
    dump, dump_path, events, install_signal_handler, last_step, record_event,
    record_step,
)
from . import core as _core
from . import flops  # noqa: F401  (automatic FLOP accounting)
from . import goodput  # noqa: F401  (per-step stall attribution)
from . import memory  # noqa: F401  (HBM/RSS attribution + live gauges)
from . import slo  # noqa: F401  (windowed SLO engine + /statusz)
from . import tracing  # noqa: F401  (distributed request/step spans)

__all__ = [
    "counter", "gauge", "histogram", "enabled", "set_enabled", "snapshot",
    "prometheus_text", "flush", "start_http_server", "get_registry",
    "record_event", "record_step", "events", "dump", "dump_path",
    "last_step", "install_signal_handler", "observe_step", "set_step_flops",
    "rank", "restart_generation", "telemetry_dir", "tracing", "flops",
    "goodput", "memory", "slo", "LATENCY_BOUNDS", "BYTE_BOUNDS",
]


# ---------------------------------------------------------------------------
# step-level instrumentation (shared by gluon.Trainer, DistributedTrainer,
# PipelineTrainer and the module.fit loop)
# ---------------------------------------------------------------------------

_STEP_FLOPS = [None]     # model FLOPs per optimizer step (fwd+bwd), if known
_PEAK_FLOPS = [False]    # False = not yet resolved; None = unknown chip


def set_step_flops(flops):
    """Declare the model's FLOPs per training step so `observe_step` can
    publish achieved MFU against `runtime.chip_peak_tflops`. Benchmarks and
    training scripts that know their FLOP count call this once;
    ``MXTPU_STEP_FLOPS`` is the env spelling."""
    _STEP_FLOPS[0] = float(flops) if flops else None


if _env.is_set("MXTPU_STEP_FLOPS"):
    _step_flops_env = _env.get("MXTPU_STEP_FLOPS")
    if _step_flops_env is not None:  # malformed value falls back to unset
        set_step_flops(_step_flops_env)


def _peak_flops():
    """Aggregate peak bf16 FLOP/s of the local devices (cached; None when
    the chip is unknown — e.g. CPU test runs)."""
    if _PEAK_FLOPS[0] is False:
        peak = None
        try:
            import jax

            from .. import runtime

            devs = jax.devices()
            per_chip = runtime.chip_peak_tflops(devs[0])
            if per_chip:
                peak = per_chip * 1e12 * len(devs)
        except Exception:
            peak = None
        _PEAK_FLOPS[0] = peak
    return _PEAK_FLOPS[0]


_STEP_METRICS = {}  # kind -> (hist, steps, examples, eps, mfu) — the per-
                    # step path must not pay 4 registry lookups per call


def _step_metrics(kind):
    m = _STEP_METRICS.get(kind)
    if m is None:
        labels = {"kind": kind}
        m = (_core._REGISTRY.histogram("mxtpu_step_seconds", labels),
             _core._REGISTRY.counter("mxtpu_steps_total", labels),
             _core._REGISTRY.counter("mxtpu_examples_total", labels),
             _core._REGISTRY.gauge("mxtpu_examples_per_sec", labels),
             _core._REGISTRY.gauge("mxtpu_step_mfu", labels),
             _core._REGISTRY.gauge("mxtpu_step_flops_auto", labels))
        _STEP_METRICS[kind] = m
    return m


def observe_step(duration_s, examples=None, step=None, kind="train"):
    """Record one completed training step: latency histogram (with a
    trace-id exemplar when the step is traced), step/example counters,
    examples/sec gauge, achieved-MFU gauge, plus the flight-recorder
    heartbeat that feeds the hang watchdog. Step FLOPs for the MFU come
    from `set_step_flops`/``MXTPU_STEP_FLOPS`` when declared, else from
    the automatic cost-analysis accounting (`telemetry.flops`) — the
    FLOPs instrumented executables actually ran since the last step."""
    if not _core._STATE.enabled:
        return
    # first step of each trainer kind registers its optional SLO
    # objectives (step-time ceiling / MFU floor / staleness — only the
    # knobs that are set); later steps pay one set-membership check
    if kind not in slo._STATE.wired_train:
        slo.wire_training(kind)
    hist, c_steps, c_examples, g_eps, g_mfu, g_auto = _step_metrics(kind)
    trace_id = tracing.current_trace_id()
    hist.observe(duration_s, exemplar=trace_id)
    # per-step peak-memory growth (device peak or VmHWM), exemplared with
    # the step's trace so a memory spike names a renderable trace
    memory.observe_step_delta(exemplar=trace_id)
    memory.ensure_poller()
    c_steps.inc()
    if examples is not None and duration_s > 0:
        c_examples.inc(int(examples))
        g_eps.set(examples / duration_s)
    auto = flops.take_step_delta() if flops.enabled() else 0.0
    step_flops = _STEP_FLOPS[0] or auto
    if step_flops and duration_s > 0:
        if auto and not _STEP_FLOPS[0]:
            g_auto.set(auto)
        peak = _peak_flops()
        if peak:
            g_mfu.set((step_flops / duration_s) / peak)
    record_step(step)


