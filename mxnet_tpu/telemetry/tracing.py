"""Distributed tracing: causal request/step spans across processes.

The metrics layer (core.py) answers "how much, how often"; this module
answers "where did THIS request/step spend its time". It is the rebuild of
the reference profiler's causal half — the dependency-engine event stream
that strung per-op timelines together — reshaped for the three-process
serving topology (HTTP server → pool router → replica worker,
docs/serving.md) and the training hot path:

  * a **trace** is one request or one training step: a 16-hex ``trace_id``
    plus a tree of **spans** (8-hex ``span_id`` / ``parent_id``), each
    with a wall-clock start, a duration, a ``component`` lane
    (server/router/worker/train) and free-form attrs;
  * **context propagation**: thread-local active-span stack in-process,
    the ``x-mxtpu-trace`` header (``<trace_id>-<span_id>-<flags>``) at
    HTTP admission, a compact tuple on the supervisor wire frames between
    router and replica, and ``MXTPU_TRACE_CONTEXT`` from the launcher to
    its workers — one trace end-to-end, whichever hops it takes;
  * **sampling**: roots record at ``MXTPU_TRACE_SAMPLE`` probability; an
    incoming context's sampled flag is always honored. The
    always-sample-on-slow escape hatch (``MXTPU_TRACE_SLOW_MS``) buffers
    unsampled local spans and emits them retroactively when the root
    overruns, so p99 outliers leave traces even at rate 0;
  * **emission**: spans land in the telemetry JSONL
    (``{"kind": "span", ...}`` lines, flushed by core.flush) carrying
    everything `tools/trace_merge.py` needs to render one
    perfetto-loadable timeline per trace across every participating
    process.

Everything is pure stdlib and lock-free on the hot path: span start/stop
is list append/pop on a thread-local stack (also registered in a plain
dict the flight recorder snapshots — a hang dump says "stuck in which
phase" directly), emission is a bounded deque append. When nothing arms
tracing (rate 0, no slow hatch, no inherited context), ``root()`` costs
one cached-bool check.
"""
from __future__ import annotations

import collections
import os
import random
import threading
import time

from .. import env as _env
from . import core

__all__ = [
    "SpanRef", "Span", "configure", "mint", "root", "span", "emit_span",
    "current", "current_trace_id", "capture", "header_value", "parse_header",
    "to_wire", "from_wire", "active_spans", "drain_pending", "set_collector",
    "HEADER", "TRACE_ID_LEN", "SPAN_ID_LEN",
]

HEADER = "x-mxtpu-trace"
TRACE_ID_LEN = 16
SPAN_ID_LEN = 8
_PENDING_MAX = 8192    # bounded emission queue (between JSONL flushes)
_BUFFER_MAX = 512      # deferred spans retained per slow-hatch trace


def _gen_id(nhex):
    # random.getrandbits is atomic under the GIL and much cheaper than
    # os.urandom per span; ids only need collision resistance within a
    # trace-retention window, not cryptographic strength
    return "%0*x" % (nhex, random.getrandbits(nhex * 4))


class _TraceState:
    """Module state in one place (reset by configure() and after fork)."""

    def __init__(self):
        self.sample = None       # None = read env lazily
        self.slow_ms = None
        self.configured = False  # explicit configure() wins over env
        self.armed = None        # cached "can anything record?" decision
        self.ambient = None      # SpanRef from MXTPU_TRACE_CONTEXT
        self.ambient_read = False
        self.collector = None    # optional in-process sink (serve_bench)


_STATE = _TraceState()
_PENDING = collections.deque(maxlen=_PENDING_MAX)   # emitted span records
_BUFFER = {}     # trace_id -> [records] awaiting a slow-hatch verdict
_TLS = threading.local()
_ACTIVE = {}     # thread ident -> that thread's span stack (the SAME list
                 # object the thread mutates; dict store/delete is atomic
                 # under the GIL, so the flight recorder can snapshot it
                 # from a signal handler without any lock)


def _reset_after_fork():
    globals()["_PENDING"] = collections.deque(maxlen=_PENDING_MAX)
    _BUFFER.clear()
    _ACTIVE.clear()
    _STATE.armed = None


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_after_fork)


def configure(sample=None, slow_ms=None):
    """Runtime override of ``MXTPU_TRACE_SAMPLE`` / ``MXTPU_TRACE_SLOW_MS``
    (tests and tools; processes normally configure via env before the
    first span). Pass None to re-read the env on next use."""
    _STATE.sample = sample
    _STATE.slow_ms = slow_ms
    _STATE.configured = sample is not None or slow_ms is not None
    _STATE.armed = None


def set_collector(fn):
    """Install (or clear, with None) an in-process span sink: every
    emitted record is also handed to ``fn(record)``. serve_bench uses this
    to aggregate phase breakdowns without reading files back."""
    _STATE.collector = fn
    _STATE.armed = None


def _sample_rate():
    if _STATE.configured:
        return _STATE.sample or 0.0
    return _env.get("MXTPU_TRACE_SAMPLE") or 0.0


def _slow_ms():
    if _STATE.configured:
        return _STATE.slow_ms
    return _env.get("MXTPU_TRACE_SLOW_MS")


def _ambient():
    """The SpanRef inherited via ``MXTPU_TRACE_CONTEXT`` (launcher →
    worker), parsed once."""
    if not _STATE.ambient_read:
        _STATE.ambient_read = True
        raw = _env.raw("MXTPU_TRACE_CONTEXT")
        if raw:
            _STATE.ambient = parse_header(raw)
    return _STATE.ambient


def _armed():
    """Can any root span record? Cached — this is the only cost on the
    hot path when tracing is off."""
    if _STATE.armed is None:
        _STATE.armed = bool(
            core._STATE.enabled
            and (_sample_rate() > 0.0 or _slow_ms() is not None
                 or _ambient() is not None or _STATE.collector is not None))
    return _STATE.armed


# ---------------------------------------------------------------------------
# references: a point in a trace (what crosses process/thread boundaries)
# ---------------------------------------------------------------------------

class SpanRef:
    """A (trace, span) coordinate plus recording flags — the value that
    travels on headers, wire frames and ``ServeRequest``s. ``sampled``
    means spans parented here are emitted immediately; ``deferred`` means
    they are buffered pending the local root's slow-hatch verdict."""

    __slots__ = ("trace_id", "span_id", "sampled", "deferred")

    def __init__(self, trace_id, span_id=None, sampled=False, deferred=False):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled
        self.deferred = deferred

    @property
    def recorded(self):
        return self.sampled or self.deferred


def mint(ref=None):
    """Mint the trace context for a new root (HTTP admission, step start):
    honor an incoming ``ref`` verbatim, else draw the sampling decision.
    Always returns a SpanRef — the ids exist (for the response header /
    correlation) even when nothing records."""
    if ref is not None:
        return ref
    if not _armed():
        return SpanRef(_gen_id(TRACE_ID_LEN))
    sampled = (_STATE.collector is not None
               or random.random() < _sample_rate())
    deferred = not sampled and _slow_ms() is not None
    return SpanRef(_gen_id(TRACE_ID_LEN), sampled=sampled, deferred=deferred)


def header_value(ref):
    """``x-mxtpu-trace`` encoding: ``<trace_id>-<span_id>-<flags>``
    (flags bit 0 = sampled)."""
    return "%s-%s-%02d" % (ref.trace_id, ref.span_id or "0" * SPAN_ID_LEN,
                           1 if ref.sampled else 0)


def parse_header(value):
    """Parse an ``x-mxtpu-trace`` header (or ``MXTPU_TRACE_CONTEXT``).
    Returns a SpanRef, or None when malformed — a bad header from a
    client must never 500 the request, it just starts a fresh trace."""
    try:
        trace_id, span_id, flags = value.strip().split("-")
        int(trace_id, 16)
        int(span_id, 16)
        return SpanRef(trace_id.lower(), span_id.lower(),
                       sampled=bool(int(flags) & 1))
    except (ValueError, AttributeError):
        return None


def to_wire(ref):
    """Compact tuple for pickle frames (router → replica worker)."""
    if ref is None:
        return None
    return (ref.trace_id, ref.span_id, bool(ref.sampled))


def from_wire(t):
    if not t:
        return None
    return SpanRef(t[0], t[1], sampled=bool(t[2]))


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class Span:
    """One live span; use via the ``root()``/``span()`` context managers.
    Doubles as a SpanRef for its children (same attribute names)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled", "deferred",
                 "name", "component", "attrs", "_t0", "_wall0", "_is_root")

    def __init__(self, name, trace_id, parent_id, sampled, deferred,
                 component, attrs, is_root):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _gen_id(SPAN_ID_LEN)
        self.parent_id = parent_id
        self.sampled = sampled
        self.deferred = deferred
        self.component = component
        self.attrs = attrs
        self._is_root = is_root
        self._t0 = time.monotonic()
        self._wall0 = time.time()

    @property
    def recorded(self):
        return self.sampled or self.deferred

    def set_attr(self, key, value):
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    # -- context manager ---------------------------------------------------
    def __enter__(self):
        stack = getattr(_TLS, "stack", None)
        if stack is None:
            stack = _TLS.stack = []
        if not stack:
            # register only while spans are open, so the table holds no
            # entries for idle/dead threads
            _ACTIVE[threading.get_ident()] = stack
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        stack = getattr(_TLS, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        elif stack is not None and self in stack:   # unbalanced exits
            stack.remove(self)
        if stack is not None and not stack:
            _ACTIVE.pop(threading.get_ident(), None)
        dur_s = time.monotonic() - self._t0
        if exc_type is not None:
            self.set_attr("error", exc_type.__name__)
        _emit(self.name, self.trace_id, self.span_id, self.parent_id,
              self.component, self._wall0, dur_s, self.attrs,
              sampled=self.sampled, deferred=self.deferred)
        if self._is_root and self.deferred:
            _settle_deferred(self.trace_id, dur_s)
        return False


class _NullSpan:
    """Shared no-op stand-in when nothing records — all API, zero cost."""

    __slots__ = ()
    trace_id = None
    span_id = None
    parent_id = None
    sampled = False
    deferred = False
    recorded = False

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set_attr(self, key, value):
        pass


_NULL = _NullSpan()


def root(name, component=None, attrs=None, ref=None):
    """Open a ROOT span: a new trace (sampling drawn via `mint`) or the
    continuation of an incoming ``ref`` (header/wire/ambient). Training
    steps parent under the launcher's ambient context automatically."""
    if ref is None:
        if not _armed():
            return _NULL
        ref = _ambient()
        if ref is not None:
            # join the launch trace; record if the launcher sampled the
            # run OR the local rate samples this step
            sampled = ref.sampled or random.random() < _sample_rate()
            deferred = not sampled and _slow_ms() is not None
            if not (sampled or deferred):
                return _NULL
            return Span(name, ref.trace_id, ref.span_id, sampled, deferred,
                        component, dict(attrs) if attrs else None, True)
        ref = mint()
    if not ref.recorded:
        return _NULL
    return Span(name, ref.trace_id, ref.span_id, ref.sampled, ref.deferred,
                component, dict(attrs) if attrs else None, True)


def span(name, component=None, attrs=None, parent=None):
    """Open a child span under ``parent`` (default: this thread's current
    span). No recording parent -> shared no-op span."""
    if parent is None:
        parent = current()
    if parent is None or not parent.recorded:
        return _NULL
    return Span(name, parent.trace_id, parent.span_id, parent.sampled,
                parent.deferred, component or getattr(parent, "component",
                                                      None),
                dict(attrs) if attrs else None, False)


def emit_span(name, start_wall, dur_s, parent, component=None, attrs=None,
              span_id=None):
    """Emit a RETROACTIVE span from measured times (phases whose start
    predates knowing they matter: queue wait, data wait). ``parent`` is a
    Span/SpanRef; returns the span id (None when not recorded).
    ``span_id`` pre-assigns the id — the pool router mints the dispatch
    span's id BEFORE the wire send so the replica can parent under it."""
    if parent is None or not parent.recorded:
        return None
    if span_id is None:
        span_id = _gen_id(SPAN_ID_LEN)
    _emit(name, parent.trace_id, span_id, parent.span_id, component,
          start_wall, dur_s, dict(attrs) if attrs else None,
          sampled=parent.sampled, deferred=parent.deferred)
    return span_id


def child_ref(parent):
    """Pre-mint a (parent-attached) SpanRef with a fresh span id, for a
    span whose record will be emitted later under that id (see
    ``emit_span(span_id=...)``). None when ``parent`` records nothing."""
    if parent is None or not parent.recorded:
        return None
    return SpanRef(parent.trace_id, _gen_id(SPAN_ID_LEN),
                   sampled=parent.sampled, deferred=parent.deferred)


def current():
    """This thread's innermost active span (None outside any span)."""
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


def current_trace_id():
    """Trace id of the active span, for histogram exemplars (None when
    no recorded span is active)."""
    sp = current()
    return sp.trace_id if sp is not None and sp.recorded else None


def capture():
    """Capture the calling thread's span context for another thread to
    parent under (ServeRequest admission). Returns a SpanRef or None."""
    sp = current()
    if sp is None or not sp.recorded:
        return None
    return SpanRef(sp.trace_id, sp.span_id, sampled=sp.sampled,
                   deferred=sp.deferred)


# ---------------------------------------------------------------------------
# emission
# ---------------------------------------------------------------------------

def _emit(name, trace_id, span_id, parent_id, component, start_wall, dur_s,
          attrs, sampled, deferred):
    rec = {
        "kind": "span",
        "name": name,
        "trace": trace_id,
        "span": span_id,
        "parent": parent_id,
        "component": component,
        "ts": start_wall,
        "dur_us": dur_s * 1e6,
        "pid": os.getpid(),
        "rank": core.rank(),
        "thread": threading.current_thread().name,
    }
    if attrs:
        rec["attrs"] = attrs
    if sampled:
        _PENDING.append(rec)
        collector = _STATE.collector
        if collector is not None:
            try:
                collector(rec)
            except Exception:
                pass  # a tool's sink must never break the traced path
        core.ensure_flusher()
    elif deferred:
        buf = _BUFFER.get(trace_id)
        if buf is None:
            buf = _BUFFER[trace_id] = []
        if len(buf) < _BUFFER_MAX:
            buf.append(rec)


def _settle_deferred(trace_id, root_dur_s):
    """Root-close verdict for an unsampled trace under the slow hatch:
    emit the buffered spans when the root overran, discard otherwise."""
    buf = _BUFFER.pop(trace_id, None)
    if not buf:
        return
    slow = _slow_ms()
    if slow is None or root_dur_s * 1e3 < slow:
        return
    for rec in buf:
        rec["slow"] = True
        _PENDING.append(rec)
    collector = _STATE.collector
    if collector is not None:
        for rec in buf:
            try:
                collector(rec)
            except Exception:
                pass
    core.ensure_flusher()


def drain_pending():
    """Hand emitted span records to the JSONL flusher (core.flush)."""
    out = []
    while True:
        try:
            out.append(_PENDING.popleft())
        except IndexError:
            return out


# ---------------------------------------------------------------------------
# flight-recorder integration
# ---------------------------------------------------------------------------

def active_spans():
    """Snapshot of every thread's currently-open spans, outermost first —
    included in flight-recorder dumps so a hang answers "stuck in which
    phase". Signal-safe by construction: iterates plain dict/list copies,
    takes no lock, allocates only small dicts."""
    now = time.monotonic()
    out = {}
    for ident, stack in list(_ACTIVE.items()):
        spans = []
        for sp in list(stack):
            spans.append({
                "name": sp.name,
                "component": sp.component,
                "trace": sp.trace_id,
                "span": sp.span_id,
                "parent": sp.parent_id,
                "open_s": round(now - sp._t0, 3),
            })
        if spans:
            out[str(ident)] = spans
    return out
