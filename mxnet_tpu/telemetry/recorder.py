"""Distributed flight recorder: recent-events ring + hang watchdog + dumps.

A slow or hung distributed run is invisible from the outside: every rank is
parked in a collective and the launcher only sees silence. This module keeps
the last ``MXTPU_FLIGHTREC_EVENTS`` telemetry events per process in a ring
buffer and knows how to dump them — together with every thread's current
stack and a metrics snapshot — to a per-rank JSON file, on three triggers:

  * watchdog — when ``MXTPU_WATCHDOG_TIMEOUT`` seconds pass without a
    training step completing (armed by the first `record_step`; the first
    step itself may compile for minutes, so nothing fires before one step
    has finished). After dumping, the default action aborts the process
    (exit code ``MXTPU_WATCHDOG_EXIT_CODE``, 43) so the launcher's group
    teardown + restart machinery takes over instead of the job hanging
    forever; ``MXTPU_WATCHDOG_ACTION=dump`` keeps the process alive and
    re-arms.
  * SIGUSR1 — `tools/launch.py` sends it to every worker just before its
    SIGTERM→SIGKILL teardown escalation, so every teardown of a hung group
    leaves one diagnosis file per rank behind. Available to operators too
    (``kill -USR1 <pid>``).
  * explicit — `dump(reason)` from code/tests.

Dumps land in ``MXTPU_TELEMETRY_DIR`` (fallback: the system temp dir) as
``flightrec-rank<R>-pid<P>.json``, and the path is announced on stderr —
which the launcher prefixes per rank into its own log, so the post-mortem
trail starts in one place. Signal-safety: the ring is a bare deque (atomic
append), metrics are lock-free (telemetry/core.py), so dumping from inside
a signal handler cannot deadlock on state the interrupted thread holds.
"""
from __future__ import annotations

import collections
import json
import os
import signal
import sys
import tempfile
import threading
import time
import traceback

from .. import env as _env
from . import core
from . import goodput  # imported HERE, not inside dump(): an import in a
from . import memory  # signal handler could deadlock on the import lock
from . import tracing

__all__ = ["record_event", "record_step", "events", "dump", "dump_path",
           "last_step", "install_signal_handler", "drain_pending_events",
           "record_alert", "alerts"]


def _ring_size():
    return max(16, _env.get("MXTPU_FLIGHTREC_EVENTS"))


class _RecState:
    def __init__(self):
        self.ring = collections.deque(maxlen=_ring_size())
        self.pending = collections.deque(maxlen=4096)  # JSONL flush queue
        # SLO breach/recovery transitions, kept SEPARATELY from the event
        # ring: a busy process churns hundreds of events between two
        # alerts, and the one question a hang dump must answer — "which
        # objective was burning?" — must not age out of a shared ring
        self.alerts = collections.deque(
            maxlen=max(4, _env.get("MXTPU_SLO_ALERTS")))
        self.last_step = None        # (step, monotonic_t, wall_t)
        self.watchdog = None
        self.watchdog_decided = False  # env checked once (hot-path guard)
        self.signal_installed = False
        self.dump_seq = 0


_REC = _RecState()


def _reset_after_fork():
    st = _RecState()
    # a forked data worker keeps the parent's history visible (harmless)
    # but gets its own watchdog/signal/pending state
    st.ring = _REC.ring.copy()
    globals()["_REC"] = st


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_after_fork)


# ---------------------------------------------------------------------------
# events
# ---------------------------------------------------------------------------

def record_event(kind, **fields):
    """Append a telemetry event to the flight-recorder ring (and queue it
    for the next JSONL flush). Cheap: two deque appends."""
    if not core._STATE.enabled:
        return
    ev = (time.time(), kind, fields)
    # bare deque appends, lock-free BY DESIGN: every thread (and the
    # watchdog) records events, and the SIGUSR1 dump path reads the ring
    # from signal context — a lock here is exactly the deadlock the
    # flight recorder exists to diagnose (module docstring)
    _REC.ring.append(ev)  # mxlint: gil-atomic — signal-safe ring
    _REC.pending.append(ev)  # mxlint: gil-atomic — signal-safe queue
    core.ensure_flusher()
    core.ensure_http()


def drain_pending_events():
    """Hand the queued (not-yet-flushed) events to the JSONL flusher."""
    out = []
    while True:
        try:
            # deque.popleft is GIL-atomic; racing flushers each drain a
            # disjoint subset (an event lands in exactly one JSONL line)
            out.append(_REC.pending.popleft())  # mxlint: gil-atomic — drain
        except IndexError:
            return out


def events():
    """Snapshot of the ring (oldest first)."""
    return [{"ts": ts, "event": kind, "fields": dict(fields)}
            for ts, kind, fields in list(_REC.ring)]


def record_alert(kind, fields):
    """Append one SLO transition (`slo_breach` / `slo_recovered`) to the
    bounded alerts ring (`MXTPU_SLO_ALERTS`). Same lock-free deque
    discipline as the event ring — dumps read it from signal context."""
    if not core._STATE.enabled:
        return
    _REC.alerts.append(  # mxlint: gil-atomic — signal-safe alerts ring
        (time.time(), kind, dict(fields or {})))


def alerts():
    """Snapshot of the alerts ring (oldest first) — carried in every
    flight-recorder dump and the /statusz page."""
    return [{"ts": ts, "event": kind, "fields": dict(fields)}
            for ts, kind, fields in list(_REC.alerts)]


def last_step():
    """(step, seconds_since) of the newest recorded step, or None."""
    ls = _REC.last_step
    if ls is None:
        return None
    return ls[0], time.monotonic() - ls[1]


def record_step(step=None):
    """Mark a training-step completion: feeds the watchdog deadline, the
    ring, and installs the SIGUSR1 handler / watchdog thread on first use."""
    if not core._STATE.enabled:
        return
    # one immutable tuple store: the watchdog reads (and on `dump` action
    # re-arms) last_step concurrently — a reader sees the old tuple or
    # the new one, never a half-written pair; locking the per-step hot
    # path is the cost this design refuses
    _REC.last_step = (step, time.monotonic(), time.time())  # mxlint: gil-atomic — tuple swap
    _REC.ring.append((time.time(), "step", {"step": step}))  # mxlint: gil-atomic — signal-safe ring
    install_signal_handler()
    _ensure_watchdog()
    core.ensure_flusher()
    core.ensure_http()


# ---------------------------------------------------------------------------
# dumps
# ---------------------------------------------------------------------------

def dump_path():
    directory = core.telemetry_dir() or tempfile.gettempdir()
    return os.path.join(directory, "flightrec-rank%d-pid%d.json"
                        % (core.rank(), os.getpid()))


def _thread_stacks():
    names = {t.ident: (t.name, t.daemon) for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        name, daemon = names.get(ident, ("unknown-%d" % ident, None))
        out.append({
            "name": name,
            "ident": ident,
            "daemon": daemon,
            "stack": [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)],
        })
    out.sort(key=lambda t: (t["name"] != "MainThread", t["name"]))
    return out


def dump(reason, path=None):
    """Write the flight-recorder dump (thread stacks + ring + metrics) and
    announce its path on stderr. Returns the path, or None on failure
    (a dump must never take the process down on its own)."""
    try:
        path = path or dump_path()
        ls = last_step()
        payload = {
            "version": 1,
            "reason": reason,
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "ts": time.time(),
            "rank": core.rank(),
            "pid": os.getpid(),
            "generation": core.restart_generation(),
            "argv": list(sys.argv),
            "last_step": None if ls is None else
                {"step": ls[0], "seconds_since": round(ls[1], 3)},
            # which phase each thread is stuck in, straight from the
            # span table (lock-free dict snapshot — signal-safe)
            "active_spans": tracing.active_spans(),
            # what was resident: RSS/VmHWM (fresh /proc read), last-polled
            # device stats, NDArray live counts, top executables by temp
            # bytes — every hang/OOM dump says where the memory went
            "memory": memory.snapshot(),
            # where the training wall-clock went: windowed goodput
            # fraction + cumulative per-phase totals (docs §Goodput;
            # lock-free value reads — signal-safe)
            "goodput": goodput.snapshot(),
            # which objective was burning when the process hung: the
            # bounded slo_breach/slo_recovered ring (docs §SLOs)
            "alerts": alerts(),
            "threads": _thread_stacks(),
            "events": events(),
            "metrics": core.snapshot(),
        }
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # raced increments (watchdog + signal + api dumps) at worst reuse
        # a tmp suffix; os.replace keeps the final dump file consistent —
        # and this path must stay lock-free (it runs in signal context)
        _REC.dump_seq += 1  # mxlint: gil-atomic — tmp-name nonce
        tmp = "%s.tmp-%d" % (path, _REC.dump_seq)
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        sys.stderr.write(
            "[flight-recorder] rank %d pid %d dumped to %s (reason: %s)\n"
            % (core.rank(), os.getpid(), path, reason))
        sys.stderr.flush()
        return path
    except Exception as e:  # diagnosis must never crash the patient
        try:
            sys.stderr.write("[flight-recorder] dump failed: %r\n" % (e,))
            sys.stderr.flush()
        except Exception:
            pass
        return None


# ---------------------------------------------------------------------------
# SIGUSR1
# ---------------------------------------------------------------------------

def _on_sigusr1(signum, frame):
    dump("SIGUSR1")
    prev = getattr(_on_sigusr1, "_prev", None)
    if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL,
                                       _on_sigusr1):
        # chaining the handler someone installed before us preserves their
        # behavior; its safety is theirs to guarantee (it would have run in
        # this same signal context had we never replaced it)
        prev(signum, frame)  # mxlint: disable=signal-safety


def install_signal_handler():
    """Install the SIGUSR1 dump handler (main thread only — elsewhere the
    attempt is silently skipped and retried from a later main-thread call).
    Chains any pre-existing handler."""
    if _REC.signal_installed or not hasattr(signal, "SIGUSR1"):
        return
    try:
        prev = signal.signal(signal.SIGUSR1, _on_sigusr1)
    except ValueError:        # not the main thread
        return
    _on_sigusr1._prev = prev
    _REC.signal_installed = True


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def _watchdog_timeout():
    t = _env.get("MXTPU_WATCHDOG_TIMEOUT")
    return t if t is not None and t > 0 else None


def _watchdog_loop(timeout):
    poll = max(0.05, min(1.0, timeout / 4.0))
    while True:
        time.sleep(poll)
        if os.getpid() != core._STATE.owner_pid:
            return
        ls = _REC.last_step
        if ls is None:
            continue
        stalled = time.monotonic() - ls[1]
        if stalled <= timeout:
            continue
        record_event("watchdog_fired", step=ls[0],
                     stalled_s=round(stalled, 3), timeout_s=timeout)
        dump("watchdog: no step completed in %.1fs (timeout %gs, last "
             "step %s)" % (stalled, timeout, ls[0]))
        core.flush(reason="watchdog")
        action = _env.get("MXTPU_WATCHDOG_ACTION").lower()
        if action == "dump":
            # keep running, re-arm from now
            # re-arm: same atomic-tuple-swap contract as record_step (a
            # step completing concurrently just re-arms again, harmless)
            _REC.last_step = (ls[0], time.monotonic(), time.time())  # mxlint: gil-atomic — tuple swap
            continue
        # a typo'd exit code must not disarm the abort (get falls back)
        code = _env.get("MXTPU_WATCHDOG_EXIT_CODE")
        sys.stderr.write(
            "[flight-recorder] rank %d aborting hung process (exit %d) so "
            "the launcher can tear down / restart the group\n"
            % (core.rank(), code))
        sys.stderr.flush()
        os._exit(code)


def _ensure_watchdog():
    # env decision cached: this sits on the per-step hot path. Configure
    # MXTPU_WATCHDOG_TIMEOUT before the first training step.
    if _REC.watchdog_decided:
        return
    _REC.watchdog_decided = True
    timeout = _watchdog_timeout()
    if timeout is None:
        return
    t = threading.Thread(target=_watchdog_loop, args=(timeout,),
                         name="mxtpu-watchdog", daemon=True)
    _REC.watchdog = t
    t.start()
