"""Telemetry core: the metrics registry and its two export paths.

The reference framework's observability was engine-side (profiler chrome
traces, KVStore counters); there was no always-on metrics layer. Large-scale
training systems (MegaScale-style production stacks, the MLPerf logging
convention) converge on the same shape: cheap always-on counters flushed as
machine-readable per-step records, plus an optional scrape endpoint. This
module is that spine for mxnet_tpu:

  * `counter` / `gauge` / `histogram` — a process-wide registry of named
    metrics. The hot path is LOCK-FREE: updates are plain attribute
    arithmetic (GIL-coalesced; a telemetry sample that loses one increment
    under thread races is acceptable, a lock on every op dispatch is not).
    This also makes every read path signal-safe — the flight recorder's
    SIGUSR1 dump can snapshot metrics without risking a deadlock on a lock
    the interrupted main thread holds. Metric creation (cold) takes the
    registry lock once.
  * JSONL flush — when ``MXTPU_TELEMETRY_DIR`` is set, a daemon thread
    appends one JSON snapshot line (+ queued events) every
    ``MXTPU_TELEMETRY_FLUSH_S`` seconds to
    ``<dir>/telemetry-rank<R>-pid<P>.jsonl``, and once more at exit.
  * Prometheus text exposition — when ``MXTPU_TELEMETRY_PORT`` is set, an
    http.server daemon thread serves ``/metrics`` on ``port + rank``
    (`start_http_server` can also be called explicitly; port 0 picks a
    free one).

Everything here is pure stdlib (no jax, no numpy) so the launcher, data
workers and test tooling can import it for free, and nothing ever adds a
hard dependency. ``MXTPU_TELEMETRY=0`` turns the whole layer into no-ops.
"""
from __future__ import annotations

import atexit
import collections
import json
import os
import threading
import time

from .. import env as _env

__all__ = [
    "enabled", "set_enabled", "counter", "gauge", "histogram", "get_registry",
    "snapshot", "prometheus_text", "flush", "start_http_server", "rank",
    "restart_generation", "telemetry_dir", "roll_windows",
    "LATENCY_BOUNDS", "BYTE_BOUNDS",
]


class _State:
    """Mutable module state in one place (re-read by tests / after fork)."""

    def __init__(self):
        self.enabled = _env.get("MXTPU_TELEMETRY")
        self.owner_pid = os.getpid()
        self.flusher = None          # flusher thread (or None)
        self.flusher_decided = False  # env checked once (hot-path guard)
        self.http_server = None      # (server, thread, port) or None
        self.http_decided = False
        self.flush_fail_logged = False
        self.last_roll = None        # wall ts of the last window roll


_STATE = _State()

# serializes the ensure_* cold paths only: record_event fires from every
# serving/telemetry thread, and an unlocked decided-flag check-then-act
# could start TWO flusher/exporter threads on a cold-start race. The hot
# path (decided flag already set) never touches this lock.
_DECIDE_LOCK = threading.Lock()


def enabled():
    """Is the metrics layer active? (``MXTPU_TELEMETRY``, default on.)"""
    return _STATE.enabled


def set_enabled(value):
    """Runtime toggle (the overhead microbenchmark and bench A-B rows use
    this; processes normally configure via ``MXTPU_TELEMETRY``)."""
    _STATE.enabled = bool(value)


def rank():
    """This process's rank from the launcher env protocol (no jax import —
    telemetry must work before/without a process group)."""
    for name in ("MXTPU_PROCESS_ID", "DMLC_WORKER_ID", "OMPI_COMM_WORLD_RANK",
                 "PMI_RANK", "SLURM_PROCID"):
        # MXTPU leg through the typed registry; scheduler vars stay raw
        # (they're other systems' protocol, not ours to register)
        v = _env.raw(name) if name.startswith("MXTPU_") \
            else os.environ.get(name)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def restart_generation():
    return _env.get("MXTPU_RESTART_GENERATION")


def telemetry_dir():
    """The JSONL/flight-recorder output directory, or None when unset."""
    return _env.raw("MXTPU_TELEMETRY_DIR") or None


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

# default histogram boundaries: step/op/collective latencies in SECONDS
LATENCY_BOUNDS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                  0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                  60.0, 120.0, 300.0)
# payload sizes in BYTES (4KiB .. 4GiB, power-of-4)
BYTE_BOUNDS = tuple(float(4096 * 4 ** i) for i in range(11))


# ---------------------------------------------------------------------------
# windowed views (docs/observability.md §SLOs)
#
# Every cumulative metric can additionally keep a bounded ring of periodic
# snapshots; diffing the live value against the newest snapshot at-or-before
# the window start yields "rate over the last 60s" / "p99 over the last 60s"
# without touching the lock-free dispatch hot path (inc/observe are
# unchanged — the roller reads cumulative state from the side). Rings are
# created at the first `roll_windows()` call, so processes that never roll
# pay nothing. Resolution is `MXTPU_SLO_WINDOW_MS`; the ring is sized to
# cover `MXTPU_SLO_SLOW_WINDOW_S` (the longest burn-rate window the SLO
# evaluator asks for), capped so a misconfigured resolution cannot grow it
# without bound.
# ---------------------------------------------------------------------------

def _window_s():
    return max(0.05, _env.get("MXTPU_SLO_WINDOW_MS") / 1e3)


def _win_maxlen(window_s):
    slow = max(60.0, _env.get("MXTPU_SLO_SLOW_WINDOW_S"))
    return max(16, min(4096, int(slow / window_s) + 2))


def _win_entries(win):
    """Stable list copy of a snapshot ring. A roller appending during the
    copy raises RuntimeError (deque mutated during iteration) — retry a
    few times; the ring mutates at window cadence, so one retry wins."""
    for _ in range(4):
        try:
            return list(win)
        except RuntimeError:
            continue
    return []


def _win_base(entries, cutoff):
    """The ring entry CLOSEST to ``cutoff`` (ties to the older side) —
    the window baseline. Picking strictly the entry before the cutoff
    would attribute everything since a long-quiet epoch's last roll to
    the window; the closest entry bounds the attribution error by half
    the roll resolution instead. Falls back to the oldest entry (partial
    coverage: the ring does not span the window yet); None on an empty
    ring."""
    older = None
    newer = None
    for e in reversed(entries):
        if e[0] <= cutoff:
            older = e
            break
        newer = e
    if older is None:
        return entries[0] if entries else None
    if newer is not None and (newer[0] - cutoff) < (cutoff - older[0]):
        return newer
    return older


def quantile_from_deltas(bounds, deltas, count, q):
    """Bucket-interpolated quantile from per-bucket counts (the windowed
    delta shape). Shared by `Histogram.windowed_quantile` and the SLO
    evaluator's multi-series merge. +Inf overflow clamps to the top
    finite bound."""
    target = max(1e-12, q * count)
    cum = 0.0
    lower = 0.0
    for bound, d in zip(bounds, deltas):
        if d:
            if cum + d >= target:
                return lower + (bound - lower) * ((target - cum) / d)
            cum += d
        lower = bound
    return bounds[-1] if bounds else None


def roll_windows(now=None, force=False):
    """Append one snapshot to every metric's window ring. Called from the
    JSONL flusher and the SLO evaluator (both off the hot path); throttled
    to the `MXTPU_SLO_WINDOW_MS` resolution so racing callers do not burn
    ring coverage. Returns the number of metrics rolled (0 when skipped)."""
    if not _STATE.enabled:
        return 0
    if now is None:
        now = time.time()
    w = _window_s()
    last = _STATE.last_roll
    if not force and last is not None and now - last < 0.9 * w:
        return 0
    # two rollers racing the throttle at worst append two entries for one
    # interval — queries diff by timestamp, so coverage only improves
    _STATE.last_roll = now  # mxlint: gil-atomic — roll-throttle stamp
    maxlen = _win_maxlen(w)
    n = 0
    for m in _REGISTRY.metrics():
        if hasattr(m, "_roll"):
            m._roll(now, maxlen)
            n += 1
    return n


def _render_labels(labels):
    if not labels:
        return ""
    inner = ",".join('%s="%s"' % (k, str(v).replace('"', '\\"'))
                     for k, v in sorted(labels.items()))
    return "{%s}" % inner


class Counter:
    """Monotonic counter (int or float). `inc` is lock-free."""

    kind = "counter"
    __slots__ = ("name", "labels", "_value", "_win", "_win_changed")

    def __init__(self, name, labels=None):
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0
        self._win = None          # snapshot ring: (ts, cumulative value)
        self._win_changed = None  # ts of the last roll that saw growth

    def inc(self, amount=1):
        if _STATE.enabled:
            self._value += amount

    @property
    def value(self):
        return self._value

    def _roll(self, now, maxlen):
        win = self._win
        v = self._value
        if win is None:
            win = self._win = collections.deque(maxlen=maxlen)
            self._win_changed = now
        elif win[-1][1] != v:
            # staleness signal: when did this counter last move?
            self._win_changed = now  # mxlint: gil-atomic — roller-only stamp
        win.append((now, v))  # mxlint: gil-atomic — lock-free ring

    def windowed_delta(self, seconds, now=None):
        """``(delta, elapsed_s)`` of this counter over the trailing window
        (diffed against the rolled ring); None before the first roll. The
        elapsed figure is the REAL baseline age — shorter than ``seconds``
        while the ring is still filling."""
        win = self._win
        if not win:
            return None
        if now is None:
            now = time.time()
        base = _win_base(_win_entries(win), now - seconds)
        if base is None:
            return None
        return (self._value - base[1], max(1e-9, now - base[0]))

    def windowed_rate(self, seconds, now=None):
        """Per-second increase over the trailing window (None: no ring)."""
        d = self.windowed_delta(seconds, now)
        if d is None:
            return None
        return d[0] / d[1]

    def seconds_since_change(self, now=None):
        """Seconds since a roll last observed this counter moving (the SLO
        staleness signal); None before the first roll."""
        ts = self._win_changed
        if ts is None:
            return None
        if now is None:
            now = time.time()
        return max(0.0, now - ts)

    def snapshot(self):
        return {"type": "counter", "value": self._value}

    def expose(self, lines):
        lines.append("%s%s %s" % (self.name, _render_labels(self.labels),
                                  _fmt_num(self._value)))


class Gauge:
    """Last-value gauge. `set`/`inc`/`dec` are lock-free."""

    kind = "gauge"
    __slots__ = ("name", "labels", "_value", "_win")

    def __init__(self, name, labels=None):
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0.0
        self._win = None  # snapshot ring: (ts, value) samples

    def set(self, value):
        if _STATE.enabled:
            self._value = value

    def inc(self, amount=1):
        if _STATE.enabled:
            self._value += amount

    def dec(self, amount=1):
        if _STATE.enabled:
            self._value -= amount

    @property
    def value(self):
        return self._value

    def _roll(self, now, maxlen):
        win = self._win
        if win is None:
            win = self._win = collections.deque(maxlen=maxlen)
        win.append((now, self._value))  # mxlint: gil-atomic — lock-free ring

    def windowed_values(self, seconds, now=None):
        """Rolled ``(ts, value)`` samples inside the trailing window, plus
        the live value as the newest sample ([] before the first roll —
        the live value alone is not window evidence)."""
        win = self._win
        if not win:
            return []
        if now is None:
            now = time.time()
        cutoff = now - seconds
        out = [(ts, v) for ts, v in _win_entries(win) if ts >= cutoff]
        out.append((now, self._value))
        return out

    def windowed_stats(self, seconds, now=None):
        """{'min','max','avg','samples'} over the trailing window, or None
        before the first roll."""
        vals = [v for _, v in self.windowed_values(seconds, now)]
        if not vals:
            return None
        return {"min": min(vals), "max": max(vals),
                "avg": sum(vals) / len(vals), "samples": len(vals)}

    def snapshot(self):
        return {"type": "gauge", "value": self._value}

    def expose(self, lines):
        lines.append("%s%s %s" % (self.name, _render_labels(self.labels),
                                  _fmt_num(self._value)))


class Histogram:
    """Fixed-boundary histogram (count/sum/min/max + cumulative buckets).

    `observe` touches a handful of attributes without a lock; a torn read
    during a concurrent snapshot skews one sample, which is the accepted
    trade for a dispatch-rate-safe hot path.
    """

    kind = "histogram"
    __slots__ = ("name", "labels", "bounds", "_counts", "_count", "_sum",
                 "_min", "_max", "_exemplars", "_win")

    def __init__(self, name, labels=None, bounds=None):
        self.name = name
        self.labels = dict(labels or {})
        self.bounds = tuple(bounds if bounds is not None else LATENCY_BOUNDS)
        self._counts = [0] * (len(self.bounds) + 1)  # last: +Inf
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._exemplars = None  # bucket index -> (value, trace_id, ts)
        self._win = None        # ring: (ts, counts tuple, count, sum)

    def observe(self, value, exemplar=None):
        """Record one observation. ``exemplar`` (a trace id) attaches the
        observation's trace to its latency bucket — the last exemplar per
        bucket is kept (Prometheus OpenMetrics semantics), so a p99
        outlier in the tail bucket links to a renderable trace."""
        if not _STATE.enabled:
            return
        i = 0
        bounds = self.bounds
        n = len(bounds)
        # linear scan beats bisect for <=~24 bounds and tiny values land
        # in the first buckets anyway
        while i < n and value > bounds[i]:
            i += 1
        self._counts[i] += 1
        self._count += 1
        self._sum += value
        if self._min is None or value < self._min:
            self._min = value
        if self._max is None or value > self._max:
            self._max = value
        if exemplar is not None:
            ex = self._exemplars
            if ex is None:
                ex = self._exemplars = {}
            ex[i] = (value, exemplar, time.time())

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def _roll(self, now, maxlen):
        win = self._win
        if win is None:
            win = self._win = collections.deque(maxlen=maxlen)
        # tuple() of the live counts list may interleave with a concurrent
        # observe — one torn sample per roll is the accepted lock-free trade
        win.append((now, tuple(self._counts), self._count,
                    self._sum))  # mxlint: gil-atomic — lock-free ring

    def windowed(self, seconds, now=None):
        """Delta view over the trailing window, diffed against the rolled
        ring: ``{'count','sum','rate','elapsed','bounds','bucket_deltas'}``
        (bucket_deltas are PER-BUCKET deltas, len(bounds)+1 with the +Inf
        overflow last). None before the first roll."""
        win = self._win
        if not win:
            return None
        if now is None:
            now = time.time()
        base = _win_base(_win_entries(win), now - seconds)
        if base is None:
            return None
        counts = list(self._counts)
        deltas = [max(0, c - b) for c, b in zip(counts, base[1])]
        dcount = max(0, self._count - base[2])
        elapsed = max(1e-9, now - base[0])
        return {"count": dcount, "sum": self._sum - base[3],
                "rate": dcount / elapsed, "elapsed": elapsed,
                "bounds": self.bounds, "bucket_deltas": deltas}

    def windowed_quantile(self, q, seconds, now=None):
        """Bucket-interpolated quantile of the observations inside the
        trailing window; None when the window saw none (or no ring yet).
        Observations in the +Inf overflow bucket clamp to the top finite
        bound — windowed quantiles can never exceed it."""
        w = self.windowed(seconds, now)
        if not w or w["count"] <= 0:
            return None
        return quantile_from_deltas(self.bounds, w["bucket_deltas"],
                                    w["count"], q)

    def _bucket_le(self, i):
        return "%g" % self.bounds[i] if i < len(self.bounds) else "+Inf"

    def exemplars(self):
        """Bucket upper-bound -> {value, trace, ts} for buckets that saw a
        traced observation ({} when none did)."""
        ex = self._exemplars
        if not ex:
            return {}
        return {self._bucket_le(i): {"value": v, "trace": t, "ts": ts}
                for i, (v, t, ts) in sorted(ex.items())}

    def snapshot(self):
        buckets = {}
        cum = 0
        for b, c in zip(self.bounds, self._counts):
            cum += c
            buckets["%g" % b] = cum
        buckets["+Inf"] = self._count
        out = {"type": "histogram", "count": self._count, "sum": self._sum,
               "min": self._min, "max": self._max, "buckets": buckets}
        ex = self.exemplars()
        if ex:
            out["exemplars"] = ex
        return out

    def expose(self, lines):
        base = dict(self.labels)
        cum = 0
        for b, c in zip(self.bounds, self._counts):
            cum += c
            lab = dict(base)
            lab["le"] = "%g" % b
            lines.append("%s_bucket%s %d" % (self.name, _render_labels(lab),
                                             cum))
        lab = dict(base)
        lab["le"] = "+Inf"
        lines.append("%s_bucket%s %d" % (self.name, _render_labels(lab),
                                         self._count))
        lines.append("%s_sum%s %s" % (self.name, _render_labels(base),
                                      _fmt_num(self._sum)))
        lines.append("%s_count%s %d" % (self.name, _render_labels(base),
                                        self._count))


def _fmt_num(v):
    if isinstance(v, float):
        return repr(v)
    return str(v)


class _NullMetric:
    """Shared no-op stand-in handed out when telemetry is hard-disabled at
    process start — call sites keep working with zero cost."""

    kind = "null"
    name = "null"
    labels: dict = {}
    value = 0
    count = 0
    sum = 0.0

    def inc(self, amount=1):
        pass

    def dec(self, amount=1):
        pass

    def set(self, value):
        pass

    def observe(self, value, exemplar=None):
        pass

    def exemplars(self):
        return {}

    def windowed_delta(self, seconds, now=None):
        return None

    def windowed_rate(self, seconds, now=None):
        return None

    def seconds_since_change(self, now=None):
        return None

    def windowed_values(self, seconds, now=None):
        return []

    def windowed_stats(self, seconds, now=None):
        return None

    def windowed(self, seconds, now=None):
        return None

    def windowed_quantile(self, q, seconds, now=None):
        return None

    def snapshot(self):
        return {"type": "null"}

    def expose(self, lines):
        pass


_NULL = _NullMetric()


class Registry:
    """Name -> metric map. Creation is locked; lookups and updates are not."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get_or_make(self, cls, name, labels, **kwargs):
        key = (name, tuple(sorted((labels or {}).items())))
        m = self._metrics.get(key)
        if m is not None:
            if not isinstance(m, cls):
                raise TypeError("telemetry metric %r already registered as %s"
                                % (name, m.kind))
            return m
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, labels, **kwargs)
                self._metrics[key] = m
        return m

    def counter(self, name, labels=None):
        return self._get_or_make(Counter, name, labels)

    def gauge(self, name, labels=None):
        return self._get_or_make(Gauge, name, labels)

    def histogram(self, name, labels=None, bounds=None):
        return self._get_or_make(Histogram, name, labels, bounds=bounds)

    def remove(self, name, labels=None):
        """Drop one metric series (exact name + labels). The SLO engine
        retires its per-objective gauges here when an objective is
        unregistered — a model unloaded mid-breach must not export a
        permanently-breaching `mxtpu_slo_healthy` series forever. Returns
        True when the series existed."""
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            return self._metrics.pop(key, None) is not None

    def metrics(self):
        # dict copy is atomic enough under the GIL; callers iterate the copy
        return list(self._metrics.values())

    def snapshot(self):
        out = {}
        for m in self.metrics():
            key = m.name + _render_labels(m.labels)
            out[key] = m.snapshot()
        return out

    def prometheus_text(self):
        typed = {}
        for m in self.metrics():
            typed.setdefault((m.name, m.kind), []).append(m)
        lines = []
        for (name, kind), ms in sorted(typed.items()):
            lines.append("# TYPE %s %s" % (name, kind))
            for m in ms:
                m.expose(lines)
        return "\n".join(lines) + "\n"


_REGISTRY = Registry()


def get_registry():
    return _REGISTRY


def counter(name, labels=None):
    if not _STATE.enabled:
        return _NULL
    return _REGISTRY.counter(name, labels)


def gauge(name, labels=None):
    if not _STATE.enabled:
        return _NULL
    return _REGISTRY.gauge(name, labels)


def histogram(name, labels=None, bounds=None):
    if not _STATE.enabled:
        return _NULL
    return _REGISTRY.histogram(name, labels, bounds)


def snapshot():
    return _REGISTRY.snapshot()


def prometheus_text():
    return _REGISTRY.prometheus_text()


# ---------------------------------------------------------------------------
# JSONL flush
# ---------------------------------------------------------------------------

def _jsonl_path(directory):
    return os.path.join(directory, "telemetry-rank%d-pid%d.jsonl"
                        % (rank(), os.getpid()))


def flush(directory=None, reason="manual"):
    """Append one metrics-snapshot line (plus any queued events) to the
    telemetry JSONL file. No-op (returns None) when no directory is
    configured; returns the path written otherwise."""
    directory = directory or telemetry_dir()
    if not directory or not _STATE.enabled:
        return None
    from . import memory
    from . import recorder
    from . import tracing

    # refresh the memory gauges (RSS/VmHWM, NDArray live, device stats)
    # so every snapshot line carries current residency figures
    memory.sample()
    # the window roller rides the flusher cadence: every flush appends one
    # ring snapshot (throttled to MXTPU_SLO_WINDOW_MS) so windowed
    # rate/quantile views stay live even without the SLO evaluator thread
    roll_windows()
    path = _jsonl_path(directory)
    try:
        os.makedirs(directory, exist_ok=True)
        lines = []
        for ev in recorder.drain_pending_events():
            lines.append(json.dumps(
                {"kind": "event", "ts": ev[0], "event": ev[1],
                 "fields": ev[2]}, default=str))
        for sp in tracing.drain_pending():
            lines.append(json.dumps(sp, default=str))
        lines.append(json.dumps({
            "kind": "metrics",
            "ts": time.time(),
            "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "rank": rank(),
            "pid": os.getpid(),
            "generation": restart_generation(),
            "reason": reason,
            "metrics": snapshot(),
        }, default=str))
        with open(path, "a") as f:
            f.write("\n".join(lines) + "\n")
        return path
    except OSError as e:
        if not _STATE.flush_fail_logged:
            # flusher/atexit/api callers race benignly: the worst case is
            # one duplicate warning line, and a lock here would put a
            # mutex on the telemetry failure path
            _STATE.flush_fail_logged = True  # mxlint: gil-atomic — warn once-ish
            import logging

            logging.getLogger("mxnet_tpu.telemetry").warning(
                "telemetry flush to %s failed: %s (further failures "
                "silenced)", directory, e)
        return None


def _flusher_loop(period):
    while True:
        time.sleep(period)
        if os.getpid() != _STATE.owner_pid:
            return  # forked child inherited the thread state marker only
        flush(reason="periodic")


def ensure_flusher():
    """Start the periodic JSONL flusher once (called lazily from the first
    instrumented event). The env decision is cached after the first look —
    this sits on the per-step hot path, so configure ``MXTPU_TELEMETRY_DIR``
    before the process starts recording (launcher/env protocol), not
    mid-run."""
    if _STATE.flusher_decided:
        return
    with _DECIDE_LOCK:  # double-checked: only the cold path locks
        if _STATE.flusher_decided:
            return
        _STATE.flusher_decided = True
        if not _STATE.enabled or not telemetry_dir():
            return
        period = _env.get("MXTPU_TELEMETRY_FLUSH_S")
        t = threading.Thread(target=_flusher_loop,
                             args=(max(0.25, period),),
                             name="mxtpu-telemetry-flush", daemon=True)
        _STATE.flusher = t
        t.start()


@atexit.register
def _flush_at_exit():
    try:
        if os.getpid() == _STATE.owner_pid:
            flush(reason="exit")
    except Exception:
        pass


def _reset_after_fork():
    """Forked children (DataLoader workers) must not inherit flusher/http
    thread markers pointing at threads that did not survive the fork; they
    restart lazily in the child if configured."""
    _STATE.owner_pid = os.getpid()
    _STATE.flusher = None
    _STATE.flusher_decided = False
    _STATE.http_server = None
    _STATE.http_decided = False


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_after_fork)


# ---------------------------------------------------------------------------
# Prometheus text-exposition endpoint
# ---------------------------------------------------------------------------

def start_http_server(port=None, addr="0.0.0.0"):
    """Serve `prometheus_text()` at /metrics on a daemon thread; returns the
    bound port. Explicit-call form of the ``MXTPU_TELEMETRY_PORT`` env path
    (port 0 binds a free port — tests). Idempotent per process."""
    if _STATE.http_server is not None:
        return _STATE.http_server[2]
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    if port is None:
        raw = _env.raw("MXTPU_TELEMETRY_PORT")
        if raw is None:
            return None
        port = int(raw)  # malformed -> ValueError, caught by ensure_http
        if port:
            # one exporter per rank on a shared host: offset by rank
            port += rank()

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?", 1)[0].rstrip("/")
            if path == "/statusz":
                # the always-on debug page (docs/observability.md §SLOs):
                # SLO verdicts + windowed rates + memory/compile/pool state
                from . import slo

                query = self.path.split("?", 1)[1] if "?" in self.path \
                    else ""
                fmt = "text" if "format=text" in query else "json"
                ctype, body = slo.render_statusz(fmt)
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path not in ("", "/metrics"):
                self.send_error(404)
                return
            from . import memory

            memory.sample()  # scrape-time residency refresh
            body = prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # no access-log spam on stderr
            pass

    server = ThreadingHTTPServer((addr, port), _Handler)
    t = threading.Thread(target=server.serve_forever,
                         name="mxtpu-telemetry-http", daemon=True)
    t.start()
    bound = server.server_address[1]
    _STATE.http_server = (server, t, bound)
    return bound


def ensure_http():
    """Start the exporter if ``MXTPU_TELEMETRY_PORT`` asks for one (lazy,
    called from the first instrumented event; env decision cached — set the
    port before the process starts recording)."""
    if _STATE.http_decided:
        return
    if not _STATE.enabled:
        return
    with _DECIDE_LOCK:  # double-checked: a cold-start race here would
        #                 bind two exporters (see ensure_flusher)
        if _STATE.http_decided:
            return
        _STATE.http_decided = True
    if _env.raw("MXTPU_TELEMETRY_PORT") is None:
        return
    try:
        start_http_server()
    except (OSError, ValueError) as e:
        # bind failure or a malformed MXTPU_TELEMETRY_PORT: telemetry must
        # never take the training process down
        import logging

        logging.getLogger("mxnet_tpu.telemetry").warning(
            "telemetry endpoint bind failed: %s (metrics endpoint disabled "
            "for this process)", e)
        _STATE.http_server = (None, None, None)  # don't retry every event
