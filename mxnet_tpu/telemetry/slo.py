"""SLO engine: declarative objectives, burn-rate evaluation, /statusz.

Every metric in `telemetry.core` is cumulative-since-process-start; an
operator (or the ROADMAP item-4 autoscaler) needs the OTHER question
answered: "is the p99 over the last 60 seconds above target, and how fast
is the error budget burning *right now*?" This module is that layer, the
way a production serving fleet does it (SRE workbook multi-window
burn-rate alerting):

  * **Objectives** — declarative, typed: latency-quantile-under-X,
    error-rate/availability, gauge ceiling/floor (queue depth, KV-page
    occupancy, MFU), staleness (a counter that stopped moving). Declared
    in code (serving/generation/training wire their own at load — see
    `wire_serving_objectives` etc.) and via a JSON spec file
    (``MXTPU_SLO_SPEC``). Malformed specs fail EAGERLY with a typed
    `SLOSpecError` — a typo'd objective silently never evaluating is an
    alert that can never fire.
  * **Evaluator** — one named daemon thread (``mxtpu-slo-evaluator``,
    PR-12 thread-hygiene conventions: named, daemon, joined by `stop`)
    rolls the window rings, computes multi-window burn rates (fast
    1m/5m page-level + slow 30m ticket-level), publishes
    ``mxtpu_slo_{healthy,burn_rate,budget_remaining}`` gauges, and emits
    ``slo_breach`` / ``slo_recovered`` flight-recorder events (with the
    offending metric's exemplar trace id) plus a bounded alerts ring the
    flight-recorder dump carries.
  * **`verdicts()`** — the programmatic hook: current per-objective
    verdicts as plain dicts (the exact surface the item-4 autoscaler
    consumes next).
  * **`/statusz`** — `statusz_payload()` fuses the verdicts with windowed
    key rates (rps, p50/p99, tokens/sec, inter-token p99), pool health +
    replica generations, compile-cache hit/persist stats, the memory
    snapshot and slowest-trace exemplars — the "what is wrong right now"
    page, served by both `ServingServer` and the telemetry exporter.
    The payload path is signal-safe BY CONSTRUCTION: it reads lock-free
    snapshots and ring diffs only, never takes a library lock, and the
    mxlint signal-safety checker walks it to keep it that way.

Burn-rate semantics: every objective reduces to a *bad fraction* over a
window and a *budget* (the allowed bad fraction). ``burn = bad/budget``;
1.0 means the budget is being consumed exactly at the allowed rate. The
page-level verdict requires EVERY fast window to burn at
``MXTPU_SLO_BURN_PAGE`` or faster (the short window proves it is
happening now, the long one that it is not a blip); the slow window
drives the ticket verdict and ``budget_remaining``.

Pure stdlib, like the rest of the telemetry spine. ``MXTPU_SLO=0``
disables the engine (rings still roll for the raw windowed views).
"""
from __future__ import annotations

import json
import os
import re
import threading
import time

from .. import env as _env
from . import core
from . import goodput
from . import memory
from . import recorder

__all__ = [
    "SLOSpecError", "Objective", "register", "unregister",
    "unregister_model", "objectives", "clear", "load_spec", "verdicts",
    "compute_verdicts", "ensure_evaluator", "start", "stop", "running",
    "statusz_payload", "render_statusz", "wire_serving_objectives",
    "wire_generate_objectives", "wire_training",
]

_METRIC_NAME_RE = re.compile(r"^mxtpu_[a-z0-9_]+$")

_KINDS = ("latency_quantile", "error_rate", "gauge_ceiling", "gauge_floor",
          "staleness")

# the eager-validation catalog: metric names an objective may target. The
# docs/observability.md Metrics table is the authoritative registry
# (metric-registry lint enforces it); this is the SUBSET that makes sense
# as an SLO signal, so a spec naming a metric that will never exist fails
# at load instead of evaluating no_data forever. Live registry names are
# also accepted (tests and bespoke instrumentation), and an objective can
# opt out with ``allow_unknown_metric``.
_SPEC_METRICS = frozenset((
    "mxtpu_serve_request_seconds", "mxtpu_serve_queue_seconds",
    "mxtpu_serve_compute_seconds", "mxtpu_serve_requests_total",
    "mxtpu_serve_rejected_total", "mxtpu_serve_http_requests_total",
    "mxtpu_serve_queue_depth", "mxtpu_serve_batch_occupancy",
    "mxtpu_serve_examples_total", "mxtpu_serve_batches_total",
    "mxtpu_serve_intertoken_seconds", "mxtpu_serve_prefill_seconds",
    "mxtpu_serve_generated_tokens_total", "mxtpu_serve_decode_steps_total",
    "mxtpu_serve_kv_pages_used", "mxtpu_serve_kv_pages_total",
    "mxtpu_serve_kv_occupancy", "mxtpu_serve_active_sequences",
    "mxtpu_serve_pool_healthy", "mxtpu_serve_pool_size",
    "mxtpu_step_seconds", "mxtpu_steps_total", "mxtpu_step_mfu",
    "mxtpu_examples_per_sec", "mxtpu_examples_total",
    "mxtpu_data_wait_seconds_total", "mxtpu_collective_seconds",
    "mxtpu_checkpoint_seconds", "mxtpu_device_bytes_in_use",
    "mxtpu_process_rss_bytes", "mxtpu_ndarray_live_bytes",
    "mxtpu_step_phase_seconds", "mxtpu_goodput_fraction",
    "mxtpu_goodput_phase_seconds_total", "mxtpu_goodput_wall_seconds_total",
    "mxtpu_checkpoint_stall_seconds",
))


class SLOSpecError(ValueError):
    """Typed error for a malformed SLO spec or objective declaration
    (bad JSON, unknown kind, unknown metric, missing/ill-typed field)."""


def enabled():
    """Is the SLO engine on? (``MXTPU_SLO``, default on; also requires the
    metrics layer itself to be enabled.)"""
    return _env.get("MXTPU_SLO") and core._STATE.enabled


def _fast_windows():
    raw = _env.raw("MXTPU_SLO_FAST_WINDOWS") or "60,300"
    out = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            w = float(part)
        except ValueError:
            continue
        if w > 0:
            out.append(w)
    return out or [60.0, 300.0]


def _eval_period_s():
    ms = _env.get("MXTPU_SLO_EVAL_MS")
    if ms is None or ms <= 0:
        return core._window_s()
    return max(0.05, ms / 1e3)


# ---------------------------------------------------------------------------
# objectives
# ---------------------------------------------------------------------------

def _check_metric_name(name, allow_unknown):
    if not isinstance(name, str) or not _METRIC_NAME_RE.match(name or ""):
        raise SLOSpecError(
            "SLO metric name %r is not a valid mxtpu_* metric name" % (name,))
    if allow_unknown or name in _SPEC_METRICS:
        return
    for m in core.get_registry().metrics():
        if m.name == name:
            return
    raise SLOSpecError(
        "SLO objective targets unknown metric %r — not in the objective "
        "catalog and not registered in this process; fix the name (see "
        "docs/observability.md Metrics table) or set "
        "allow_unknown_metric=true" % (name,))


def _check_selectors(field, raw, allow_unknown):
    """Normalize an error_rate selector list to [(name, labels), ...]."""
    if not isinstance(raw, (list, tuple)) or not raw:
        raise SLOSpecError("error_rate objective needs a non-empty %r "
                           "selector list" % (field,))
    out = []
    for sel in raw:
        if isinstance(sel, str):
            name, labels = sel, {}
        elif isinstance(sel, (list, tuple)) and len(sel) == 2:
            name, labels = sel
        elif isinstance(sel, dict):
            name, labels = sel.get("metric"), sel.get("labels") or {}
        else:
            raise SLOSpecError("bad %r selector %r (want a metric name, "
                               "(name, labels) pair, or {'metric':, "
                               "'labels':})" % (field, sel))
        if not isinstance(labels, dict):
            raise SLOSpecError("selector labels for %r must be an object, "
                               "got %r" % (name, labels))
        _check_metric_name(name, allow_unknown)
        out.append((name, dict(labels)))
    return out


class Objective:
    """One declarative objective. Validation is EAGER: a malformed
    declaration raises `SLOSpecError` at construction, never at
    evaluation time."""

    __slots__ = ("name", "kind", "metric", "labels", "threshold", "quantile",
                 "budget", "bad", "total", "fast_windows", "slow_window",
                 "burn_page", "burn_ticket", "description")

    def __init__(self, name, kind, metric=None, labels=None, threshold=None,
                 quantile=0.99, budget=None, bad=None, total=None,
                 fast_windows=None, slow_window=None, burn_page=None,
                 burn_ticket=None, description="",
                 allow_unknown_metric=False):
        if not name or not isinstance(name, str):
            raise SLOSpecError("objective needs a non-empty string name, "
                              "got %r" % (name,))
        if kind not in _KINDS:
            raise SLOSpecError("objective %r: unknown kind %r (one of %s)"
                               % (name, kind, "|".join(_KINDS)))
        self.name = name
        self.kind = kind
        self.labels = dict(labels or {})
        self.description = description or ""
        if kind == "error_rate":
            self.metric = None
            self.bad = _check_selectors("bad", bad, allow_unknown_metric)
            self.total = _check_selectors("total", total,
                                          allow_unknown_metric)
            if budget is None:
                raise SLOSpecError(
                    "error_rate objective %r needs a budget (allowed bad "
                    "fraction, e.g. 0.001) or an availability target"
                    % name)
        else:
            if bad or total:
                raise SLOSpecError("objective %r: bad=/total= selectors "
                                   "are error_rate-only" % name)
            _check_metric_name(metric, allow_unknown_metric)
            self.metric = metric
            self.bad = self.total = None
            if threshold is None:
                raise SLOSpecError("objective %r (%s) needs a threshold"
                                   % (name, kind))
        if threshold is not None:
            try:
                threshold = float(threshold)
            except (TypeError, ValueError):
                raise SLOSpecError("objective %r: threshold %r is not a "
                                   "number" % (name, threshold)) from None
            if threshold <= 0 and kind != "gauge_floor":
                raise SLOSpecError("objective %r: threshold must be > 0, "
                                   "got %g" % (name, threshold))
        self.threshold = threshold
        try:
            quantile = float(quantile)
        except (TypeError, ValueError):
            raise SLOSpecError("objective %r: quantile %r is not a number"
                               % (name, quantile)) from None
        if not 0.0 < quantile < 1.0:
            raise SLOSpecError("objective %r: quantile must be in (0, 1), "
                               "got %g" % (name, quantile))
        self.quantile = quantile
        if budget is None:
            # latency: the quantile IS the budget (p99 => 1% may be slow);
            # gauges: a quarter of the window's samples may violate before
            # the objective burns at rate 1
            budget = (1.0 - quantile) if kind == "latency_quantile" else 0.25
        try:
            budget = float(budget)
        except (TypeError, ValueError):
            raise SLOSpecError("objective %r: budget %r is not a number"
                               % (name, budget)) from None
        if not 0.0 < budget <= 1.0:
            raise SLOSpecError("objective %r: budget must be in (0, 1], "
                               "got %g" % (name, budget))
        self.budget = budget
        self.fast_windows = [float(w) for w in
                             (fast_windows or _fast_windows())]
        if not self.fast_windows or min(self.fast_windows) <= 0:
            raise SLOSpecError("objective %r: fast_windows must be "
                               "positive seconds" % name)
        self.slow_window = float(slow_window if slow_window is not None
                                 else _env.get("MXTPU_SLO_SLOW_WINDOW_S"))
        self.burn_page = float(burn_page if burn_page is not None
                               else _env.get("MXTPU_SLO_BURN_PAGE"))
        self.burn_ticket = float(burn_ticket if burn_ticket is not None
                                 else _env.get("MXTPU_SLO_BURN_TICKET"))

    _SPEC_KEYS = frozenset((
        "name", "kind", "metric", "labels", "threshold", "threshold_ms",
        "quantile", "budget", "availability", "bad", "total",
        "fast_windows", "slow_window", "burn_page", "burn_ticket",
        "description", "allow_unknown_metric"))

    @classmethod
    def from_spec(cls, entry):
        """One objective from a spec-file JSON object. Unknown keys are an
        eager error (a typo'd ``treshold_ms`` must not silently leave the
        default in force)."""
        if not isinstance(entry, dict):
            raise SLOSpecError("spec objective must be a JSON object, got "
                               "%r" % (entry,))
        unknown = sorted(set(entry) - cls._SPEC_KEYS)
        if unknown:
            raise SLOSpecError("spec objective %r: unknown key(s) %s"
                               % (entry.get("name"), ", ".join(unknown)))
        kwargs = {k: entry[k] for k in entry
                  if k in cls._SPEC_KEYS and k not in
                  ("name", "kind", "threshold_ms", "availability")}
        threshold = entry.get("threshold")
        if entry.get("threshold_ms") is not None:
            if threshold is not None:
                raise SLOSpecError("spec objective %r: give threshold OR "
                                   "threshold_ms, not both"
                                   % entry.get("name"))
            try:
                threshold = float(entry["threshold_ms"]) / 1e3
            except (TypeError, ValueError):
                raise SLOSpecError(
                    "spec objective %r: threshold_ms %r is not a number"
                    % (entry.get("name"),
                       entry.get("threshold_ms"))) from None
        kwargs["threshold"] = threshold
        if entry.get("availability") is not None:
            if entry.get("budget") is not None:
                raise SLOSpecError("spec objective %r: give budget OR "
                                   "availability, not both"
                                   % entry.get("name"))
            try:
                avail = float(entry["availability"])
            except (TypeError, ValueError):
                raise SLOSpecError(
                    "spec objective %r: availability %r is not a number"
                    % (entry.get("name"), entry.get("availability"))) \
                    from None
            if not 0.0 < avail < 1.0:
                raise SLOSpecError("spec objective %r: availability must "
                                   "be in (0, 1)" % entry.get("name"))
            kwargs["budget"] = 1.0 - avail
        return cls(entry.get("name"), entry.get("kind"), **kwargs)

    def to_dict(self):
        return {"name": self.name, "kind": self.kind, "metric": self.metric,
                "labels": dict(self.labels), "threshold": self.threshold,
                "quantile": self.quantile, "budget": self.budget,
                "bad": self.bad, "total": self.total,
                "fast_windows": list(self.fast_windows),
                "slow_window": self.slow_window,
                "burn_page": self.burn_page,
                "burn_ticket": self.burn_ticket,
                "description": self.description}


# ---------------------------------------------------------------------------
# engine state
# ---------------------------------------------------------------------------

class _SLOState:
    def __init__(self):
        self.owner_pid = os.getpid()
        self.objectives = {}      # name -> Objective (writes under _REG_LOCK)
        self.spec_objectives = {}  # name -> Objective as declared in the
        #                            spec file — survives unregister_model
        #                            so a model reload restores them
        self.thread = None        # evaluator thread (or None)
        self.stop_event = None
        self.spec_loaded = False
        self.last_verdicts = None  # {"ts":, "verdicts": [...]} plain swap
        self.breaching = {}        # objective name -> breach-start ts
        self.wired_train = set()   # trainer kinds already wired
        self.eval_errors = 0


_STATE = _SLOState()

# serializes registration/spec-load/evaluator start-stop (cold paths);
# NEVER taken on the verdict-compute / statusz read path, which stays
# lock-free by construction (the signal-safety checker walks it)
_REG_LOCK = threading.Lock()


def _reset_after_fork():
    st = _SLOState()
    st.objectives = dict(_STATE.objectives)  # declarations survive the fork
    st.spec_objectives = dict(_STATE.spec_objectives)
    st.spec_loaded = _STATE.spec_loaded
    st.wired_train = set(_STATE.wired_train)
    globals()["_STATE"] = st


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_reset_after_fork)


def register(objective, replace=True):
    """Register (or replace) one objective; starts the evaluator when the
    engine is enabled. Returns the registered objective."""
    if not isinstance(objective, Objective):
        raise SLOSpecError("register() wants an Objective, got %r"
                           % (objective,))
    with _REG_LOCK:
        if not replace and objective.name in _STATE.objectives:
            return _STATE.objectives[objective.name]
        _STATE.objectives[objective.name] = objective
    ensure_evaluator()
    return objective


def _drop_gauges(name):
    """Retire one objective's published gauge series: a model unloaded
    while breaching must not export `mxtpu_slo_healthy{...}=0` forever —
    an alert that could never resolve."""
    reg = core.get_registry()
    labels = {"slo": name}
    for mname in ("mxtpu_slo_healthy", "mxtpu_slo_burn_rate",
                  "mxtpu_slo_budget_remaining"):
        reg.remove(mname, labels)


def unregister(name):
    """Drop one objective by name (idempotent), retiring its gauges."""
    with _REG_LOCK:
        _STATE.objectives.pop(name, None)
        _STATE.breaching.pop(name, None)
    _drop_gauges(name)  # outside _REG_LOCK: registry lock stays a leaf


def unregister_model(model_label):
    """Drop every objective scoped to a served model (its batcher/scheduler
    is closing; verdicts for a gone model are noise)."""
    with _REG_LOCK:
        dropped = [n for n, o in _STATE.objectives.items()
                   if o.labels.get("model") == model_label]
        for name in dropped:
            _STATE.objectives.pop(name, None)
            _STATE.breaching.pop(name, None)
    for name in dropped:
        _drop_gauges(name)


def objectives():
    """Registered objectives (copy; dict copy is GIL-atomic — no lock on
    the read path)."""
    return list(_STATE.objectives.values())


def clear():
    """Drop every objective (tests)."""
    with _REG_LOCK:
        _STATE.objectives.clear()
        _STATE.breaching.clear()
        _STATE.spec_objectives.clear()
        _STATE.spec_loaded = False


# ---------------------------------------------------------------------------
# spec file
# ---------------------------------------------------------------------------

def load_spec(path=None):
    """Load objectives from a JSON spec file (default: ``MXTPU_SLO_SPEC``)
    and register them. Returns the objectives registered. Every failure is
    a typed, EAGER `SLOSpecError`."""
    path = path or _env.raw("MXTPU_SLO_SPEC")
    if not path:
        return []
    try:
        with open(path) as f:
            raw = f.read()
    except OSError as e:
        raise SLOSpecError("cannot read SLO spec %s: %s" % (path, e)) \
            from None
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise SLOSpecError("SLO spec %s is not valid JSON: %s" % (path, e)) \
            from None
    if not isinstance(doc, dict) or not isinstance(doc.get("objectives"),
                                                   list):
        raise SLOSpecError("SLO spec %s must be an object with an "
                           "'objectives' array" % path)
    objs = [Objective.from_spec(entry) for entry in doc["objectives"]]
    for obj in objs:
        with _REG_LOCK:
            # remembered separately: unregister_model drops the LIVE
            # objective when its model unloads, but a reload of the same
            # model must restore the operator's declaration, not fall
            # back to the env-default built-in
            _STATE.spec_objectives[obj.name] = obj
        register(obj)
    return objs


def _restore_spec_for(model_label):
    """Re-register the spec file's objectives scoped to a (re)loading
    model — replace=True, so they beat the just-wired built-ins."""
    for obj in list(_STATE.spec_objectives.values()):
        if obj.labels.get("model") == model_label:
            register(obj)


def _ensure_spec():
    if _STATE.spec_loaded:
        return
    with _REG_LOCK:
        if _STATE.spec_loaded:
            return
        # set BEFORE loading: load_spec -> register -> ensure_evaluator
        # re-enters here, and the flag is the recursion guard
        _STATE.spec_loaded = True
    if _env.raw("MXTPU_SLO_SPEC"):
        try:
            load_spec()
        except Exception:
            # a failed load must not latch: the operator fixes the spec
            # file and the next model load retries (and re-raises) —
            # otherwise the corrected objectives silently never register
            _STATE.spec_loaded = False  # mxlint: gil-atomic — unlatch on failure
            raise


# ---------------------------------------------------------------------------
# evaluation (lock-free: ring diffs + live values only — this is the path
# /statusz and the signal-safety walk go through)
# ---------------------------------------------------------------------------

def _metric_index():
    """One name -> [metric series] map from a single registry scan —
    every selector lookup in a compute_verdicts pass resolves against it
    instead of re-walking the whole registry per selector per window."""
    idx = {}
    for m in core.get_registry().metrics():
        idx.setdefault(m.name, []).append(m)
    return idx


def _match(name, labels, index=None):
    """Every registered metric with this name whose labels are a superset
    of ``labels`` (multi-series selectors sum across e.g. the rejection
    reasons of one model)."""
    if index is None:
        index = _metric_index()
    out = []
    for m in index.get(name, ()):
        ml = m.labels
        ok = True
        for k, v in (labels or {}).items():
            if ml.get(k) != v:
                ok = False
                break
        if ok:
            out.append(m)
    return out


def _counter_window(selectors, seconds, now, index=None):
    """Summed (delta, elapsed) across selector-matched counters over the
    trailing window; None when no matched counter has a ring yet."""
    delta = 0.0
    elapsed = 0.0
    seen = False
    for name, labels in selectors:
        for m in _match(name, labels, index):
            if not hasattr(m, "windowed_delta"):
                continue
            d = m.windowed_delta(seconds, now)
            if d is None:
                continue
            seen = True
            delta += d[0]
            if d[1] > elapsed:
                elapsed = d[1]
    if not seen:
        return None
    return (delta, elapsed)


def _merged_hist_window(name, labels, seconds, now, index=None):
    """Bucket-delta window merged across every matching histogram series
    (same metric name => same bounds by construction); None when no
    series has a ring yet."""
    bounds = None
    deltas = None
    count = 0
    total = 0.0
    elapsed = 0.0
    for m in _match(name, labels, index):
        if not hasattr(m, "windowed"):
            continue
        w = m.windowed(seconds, now)
        if w is None:
            continue
        if bounds is None:
            bounds = w["bounds"]
            deltas = list(w["bucket_deltas"])
        elif w["bounds"] == bounds:
            deltas = [a + b for a, b in zip(deltas, w["bucket_deltas"])]
        else:
            continue  # mismatched custom bounds: skip rather than corrupt
        count += w["count"]
        total += w["sum"]
        if w["elapsed"] > elapsed:
            elapsed = w["elapsed"]
    if bounds is None:
        return None
    return {"bounds": bounds, "bucket_deltas": deltas, "count": count,
            "sum": total, "elapsed": elapsed}


def _frac_over(bounds, deltas, count, threshold):
    """Fraction of windowed observations above ``threshold``. Buckets
    whose upper bound is <= threshold are provably good; the bucket
    spanning the threshold counts bad (conservative)."""
    if count <= 0:
        return 0.0
    good = 0.0
    for bound, d in zip(bounds, deltas):
        if bound <= threshold:
            good += d
        else:
            break
    return max(0.0, count - good) / count


def _window_burn(obj, seconds, now, index=None):
    """One window's burn figure for one objective:
    {'burn','value','count','no_data'} — burn 1.0 = consuming the error
    budget exactly at the allowed rate over this window."""
    if obj.kind == "latency_quantile":
        w = _merged_hist_window(obj.metric, obj.labels, seconds, now,
                                index)
        if w is None or w["count"] <= 0:
            return {"burn": 0.0, "value": None, "count": 0, "no_data": True}
        value = core.quantile_from_deltas(w["bounds"], w["bucket_deltas"],
                                          w["count"], obj.quantile)
        bad = _frac_over(w["bounds"], w["bucket_deltas"], w["count"],
                         obj.threshold)
        return {"burn": min(1e6, bad / obj.budget), "value": value,
                "count": w["count"], "no_data": False}
    if obj.kind == "error_rate":
        total = _counter_window(obj.total, seconds, now, index)
        if total is None or total[0] <= 0:
            return {"burn": 0.0, "value": None, "count": 0, "no_data": True}
        bad = _counter_window(obj.bad, seconds, now, index)
        frac = max(0.0, (bad[0] if bad else 0.0)) / total[0]
        return {"burn": min(1e6, frac / obj.budget), "value": frac,
                "count": int(total[0]), "no_data": False}
    if obj.kind in ("gauge_ceiling", "gauge_floor"):
        samples = []
        for m in _match(obj.metric, obj.labels, index):
            if hasattr(m, "windowed_values"):
                samples.extend(v for _, v in
                               m.windowed_values(seconds, now))
        if not samples:
            return {"burn": 0.0, "value": None, "count": 0, "no_data": True}
        if obj.kind == "gauge_ceiling":
            viol = sum(1 for v in samples if v > obj.threshold)
            value = max(samples)
        else:
            viol = sum(1 for v in samples if v < obj.threshold)
            value = min(samples)
        frac = viol / float(len(samples))
        return {"burn": min(1e6, frac / obj.budget), "value": value,
                "count": len(samples), "no_data": False}
    # staleness: seconds since the counter last moved, vs the threshold
    stale = None
    for m in _match(obj.metric, obj.labels, index):
        if not hasattr(m, "seconds_since_change"):
            continue
        s = m.seconds_since_change(now)
        if s is not None and (stale is None or s < stale):
            stale = s  # ANY live series keeps the signal fresh
    if stale is None:
        return {"burn": 0.0, "value": None, "count": 0, "no_data": True}
    return {"burn": min(1e6, stale / obj.threshold), "value": stale,
            "count": 1, "no_data": False}


def _exemplar_for(obj, index=None):
    """The offending metric's tail exemplar (highest-bucket traced
    observation) for a latency objective — the trace id a breach event
    names so the page links to a renderable trace."""
    if obj.kind != "latency_quantile":
        return None
    best = None
    for m in _match(obj.metric, obj.labels, index):
        if not hasattr(m, "exemplars"):
            continue
        for ex in m.exemplars().values():
            if best is None or ex["value"] > best["value"]:
                best = ex
    return best


def _eval_objective(obj, now, index=None):
    """Full multi-window verdict for one objective (a plain dict — the
    `verdicts()` API shape)."""
    if index is None:
        index = _metric_index()
    windows = {}
    for w in obj.fast_windows:
        windows["%gs" % w] = dict(_window_burn(obj, w, now, index),
                                   window_s=w)
    slow_key = "%gs" % obj.slow_window
    if slow_key not in windows:
        windows[slow_key] = dict(_window_burn(obj, obj.slow_window, now,
                                              index),
                                 window_s=obj.slow_window)
    fast = [windows["%gs" % w] for w in obj.fast_windows]
    slow = windows[slow_key]
    fast_with_data = [r for r in fast if not r["no_data"]]
    page = bool(fast) and len(fast_with_data) == len(fast) and \
        min(r["burn"] for r in fast) >= obj.burn_page
    ticket = (not slow["no_data"]) and slow["burn"] >= obj.burn_ticket
    burn = max((r["burn"] for r in fast_with_data), default=0.0)
    no_data = not fast_with_data and slow["no_data"]
    if slow["no_data"]:
        budget_remaining = None
    else:
        budget_remaining = min(1.0, max(0.0, 1.0 - slow["burn"]))
    value = fast_with_data[0]["value"] if fast_with_data else None
    ex = _exemplar_for(obj, index)
    return {
        "slo": obj.name,
        "kind": obj.kind,
        "metric": obj.metric or [s[0] for s in (obj.bad or [])],
        "labels": dict(obj.labels),
        "description": obj.description,
        "threshold": obj.threshold,
        "quantile": obj.quantile if obj.kind == "latency_quantile" else None,
        "budget": obj.budget,
        "healthy": not page,
        "page": page,
        "ticket": ticket,
        "no_data": no_data,
        "burn_rate": round(burn, 4),
        "budget_remaining": budget_remaining,
        "value": value,
        "windows": windows,
        "exemplar_trace": ex["trace"] if ex else None,
        "exemplar_value": ex["value"] if ex else None,
    }


def compute_verdicts(now=None):
    """Evaluate every registered objective against the current window
    rings (rolling them first, throttled). Pure reads — safe from any
    thread, never takes a library lock, never publishes gauges or events
    (that is the evaluator loop's job)."""
    if now is None:
        now = time.time()
    core.roll_windows(now)
    index = _metric_index()  # ONE registry scan for the whole pass
    return [_eval_objective(obj, now, index) for obj in objectives()]


def verdicts():
    """Current per-objective verdicts: the evaluator's last published set
    when fresh, else computed on the spot. THE programmatic hook the
    item-4 autoscaler consumes (scale up when a queue-depth/p99 verdict
    pages, scale down when budgets sit untouched)."""
    return _fresh_verdicts(time.time(), update=True)


# ---------------------------------------------------------------------------
# evaluator thread
# ---------------------------------------------------------------------------

def _slo_gauges(name):
    labels = {"slo": name}
    reg = core.get_registry()
    return (reg.gauge("mxtpu_slo_healthy", labels),
            reg.gauge("mxtpu_slo_burn_rate", labels),
            reg.gauge("mxtpu_slo_budget_remaining", labels))


def _publish(verds, now):
    """Gauge + transition-event publication (evaluator thread only, so
    breach/recovery transitions are single-writer)."""
    for v in verds:
        name = v["slo"]
        if name not in _STATE.objectives:
            continue  # unregistered since this lap's compute: don't
            #           resurrect the gauges _drop_gauges just retired
        g_ok, g_burn, g_budget = _slo_gauges(name)
        g_ok.set(1 if v["healthy"] else 0)
        g_burn.set(v["burn_rate"])
        if v["budget_remaining"] is not None:
            g_budget.set(v["budget_remaining"])
        since = _STATE.breaching.get(name)
        if v["page"] and since is None:
            # transition state is SINGLE-WRITER (this runs only on the
            # evaluator thread); the registration paths' locked pops only
            # delete entries for objectives being dropped entirely
            _STATE.breaching[name] = now  # mxlint: gil-atomic — evaluator-only transition state
            fields = {"slo": name, "objective_kind": v["kind"],
                      "metric": v["metric"], "labels": v["labels"],
                      "burn_rate": v["burn_rate"],
                      "threshold": v["threshold"], "value": v["value"],
                      "budget_remaining": v["budget_remaining"],
                      "exemplar_trace": v["exemplar_trace"]}
            recorder.record_event("slo_breach", **fields)
            recorder.record_alert("slo_breach", fields)
        elif since is not None and not v["page"]:
            _STATE.breaching.pop(name, None)  # mxlint: gil-atomic — evaluator-only transition state
            fields = {"slo": name, "objective_kind": v["kind"],
                      "burned_for_s": round(now - since, 3),
                      "burn_rate": v["burn_rate"], "value": v["value"]}
            recorder.record_event("slo_recovered", **fields)
            recorder.record_alert("slo_recovered", fields)
        if name not in _STATE.objectives:
            # unregister_model ran BETWEEN the membership check above and
            # the gauge writes: self-heal by retiring what we just set
            # (whichever of the two drops runs last leaves a clean state)
            _STATE.breaching.pop(name, None)  # mxlint: gil-atomic — evaluator-only transition state
            _drop_gauges(name)


def _evaluate_and_publish(now=None):
    if now is None:
        now = time.time()
    verds = compute_verdicts(now)
    # whole-dict swap; statusz/verdicts() readers see old or new, whole
    _STATE.last_verdicts = {"ts": now, "verdicts": verds}  # mxlint: gil-atomic — whole-dict swap
    _publish(verds, now)
    return verds


def _evaluator_loop(stop_event):
    # stop_event captured as a local (PR-12 io.py lesson): a stop()/start()
    # cycle replaces _STATE.stop_event, and the OLD thread must keep
    # honoring the event it was started with
    while not stop_event.wait(_eval_period_s()):
        if os.getpid() != _STATE.owner_pid:
            return  # forked child inherited the state marker only
        if not enabled():
            continue  # runtime-disabled: keep the thread, skip the work
        try:
            _evaluate_and_publish()
        except Exception as e:  # the evaluator must never die
            _STATE.eval_errors += 1  # mxlint: gil-atomic — error tally
            recorder.record_event("slo_evaluator_error", error=repr(e))


def ensure_evaluator():
    """Start the evaluator once objectives exist and the engine is enabled
    (lazy; called from registration). Idempotent."""
    if _STATE.thread is not None or not enabled():
        return
    _ensure_spec()
    with _REG_LOCK:
        if _STATE.thread is not None or not _STATE.objectives:
            return
        ev = threading.Event()
        t = threading.Thread(target=_evaluator_loop, args=(ev,),
                             name="mxtpu-slo-evaluator", daemon=True)
        _STATE.stop_event = ev
        _STATE.thread = t
        # start INSIDE the lock: a concurrent stop() that wins the lock
        # next must never see (and try to join) a not-yet-started thread
        t.start()


def start():
    """Explicit evaluator start (loads ``MXTPU_SLO_SPEC`` first)."""
    _ensure_spec()
    ensure_evaluator()
    return running()


def stop(join=True):
    """Stop (and join) the evaluator thread; a later register()/start()
    spawns a fresh one."""
    with _REG_LOCK:
        t = _STATE.thread
        ev = _STATE.stop_event
        _STATE.thread = None
        _STATE.stop_event = None
    if t is None:
        return
    if ev is not None:
        ev.set()
    if join:
        t.join(timeout=5.0)


def running():
    t = _STATE.thread
    return t is not None and t.is_alive()


# ---------------------------------------------------------------------------
# built-in objective wiring (serving / generation / training)
# ---------------------------------------------------------------------------

def wire_serving_objectives(model_label, queue_depth=None):
    """Default serving objectives for one served model, registered at
    batcher creation: request-latency p99, availability, queue-depth
    ceiling. Thresholds come from the ``MXTPU_SLO_SERVE_*`` env knobs; a
    spec file can replace any of them by registering the same name."""
    if not enabled():
        return
    labels = {"model": model_label}
    # replace=False: an operator's MXTPU_SLO_SPEC objective of the same
    # name (loaded before the model) must win over the env-default one
    register(Objective(
        "serve-p99:%s" % model_label, "latency_quantile",
        metric="mxtpu_serve_request_seconds", labels=labels,
        quantile=0.99,
        threshold=_env.get("MXTPU_SLO_SERVE_P99_MS") / 1e3,
        description="p99 request latency (admission to resolution)"),
        replace=False)
    avail = _env.get("MXTPU_SLO_SERVE_AVAILABILITY")
    register(Objective(
        "serve-availability:%s" % model_label, "error_rate",
        bad=[("mxtpu_serve_rejected_total", labels)],
        # denominator = every request that ASKED: admitted ones land in
        # requests_total (deadline expiries included — they were
        # admitted, so adding rejected{deadline} here would double-count
        # them and halve the measured burn in a pure-504 outage);
        # queue-full/shed rejections never reach requests_total and are
        # added explicitly
        total=[("mxtpu_serve_requests_total", labels),
               ("mxtpu_serve_rejected_total",
                dict(labels, reason="queue_full")),
               ("mxtpu_serve_rejected_total", dict(labels, reason="shed"))],
        budget=max(1e-6, 1.0 - avail), labels=labels,
        description="fraction of requests deterministically rejected "
                    "(429 queue-full, 504 deadline, 503 shed)"),
        replace=False)
    if queue_depth:
        register(Objective(
            "serve-queue-depth:%s" % model_label, "gauge_ceiling",
            metric="mxtpu_serve_queue_depth", labels=labels,
            threshold=max(1.0, _env.get("MXTPU_SLO_SERVE_QUEUE_FRAC")
                          * queue_depth),
            description="admission queue sitting near its depth limit "
                        "(the page before 429s; the autoscaler's "
                        "scale-up signal)"),
            replace=False)
    # a reload of a model whose spec objectives were dropped at unload
    # gets the operator's declarations back, not just the env defaults
    _restore_spec_for(model_label)


def wire_generate_objectives(model_label, queue_depth=None):
    """Default generation-serving objectives: inter-token p99 + KV-page
    occupancy ceiling (+ the shared queue-depth ceiling)."""
    if not enabled():
        return
    labels = {"model": model_label}
    # replace=False: spec-file objectives of the same name take precedence
    register(Objective(
        "serve-intertoken-p99:%s" % model_label, "latency_quantile",
        metric="mxtpu_serve_intertoken_seconds", labels=labels,
        quantile=0.99,
        threshold=_env.get("MXTPU_SLO_INTERTOKEN_P99_MS") / 1e3,
        description="p99 latency between consecutive tokens of one "
                    "sequence (what a streaming client feels)"),
        replace=False)
    register(Objective(
        "serve-kv-occupancy:%s" % model_label, "gauge_ceiling",
        metric="mxtpu_serve_kv_occupancy", labels=labels,
        threshold=_env.get("MXTPU_SLO_KV_OCCUPANCY"),
        description="KV-page pool occupancy (used/total); pinned above "
                    "the ceiling means admissions queue on page "
                    "pressure"), replace=False)
    if queue_depth:
        register(Objective(
            "serve-queue-depth:%s" % model_label, "gauge_ceiling",
            metric="mxtpu_serve_queue_depth", labels=labels,
            threshold=max(1.0, _env.get("MXTPU_SLO_SERVE_QUEUE_FRAC")
                          * queue_depth),
            description="generation admission queue near its depth "
                        "limit"), replace=False)
    _restore_spec_for(model_label)


def wire_training(kind):
    """Optional training objectives per trainer kind, registered at the
    first `observe_step` for that kind — only when the matching
    ``MXTPU_SLO_STEP_*`` / ``MXTPU_SLO_MFU_FLOOR`` knob is set (a CPU
    test run must not page on MFU)."""
    wired = _STATE.wired_train
    if kind in wired:
        return
    wired.add(kind)  # mxlint: gil-atomic — idempotent set add
    if not enabled():
        return
    labels = {"kind": kind}
    step_s = _env.get("MXTPU_SLO_STEP_SECONDS")
    if step_s:
        register(Objective(
            "train-step-p99:%s" % kind, "latency_quantile",
            metric="mxtpu_step_seconds", labels=labels, quantile=0.99,
            threshold=step_s,
            description="p99 optimizer-step wall time"), replace=False)
    mfu = _env.get("MXTPU_SLO_MFU_FLOOR")
    if mfu:
        register(Objective(
            "train-mfu-floor:%s" % kind, "gauge_floor",
            metric="mxtpu_step_mfu", labels=labels, threshold=mfu,
            description="achieved-MFU floor (input starvation / "
                        "de-optimized step / sick chip)"), replace=False)
    stale_s = _env.get("MXTPU_SLO_STEP_STALENESS_S")
    if stale_s:
        register(Objective(
            "train-step-staleness:%s" % kind, "staleness",
            metric="mxtpu_steps_total", labels=labels, threshold=stale_s,
            description="seconds without a completed step (SLO-shaped "
                        "watchdog)"), replace=False)
    goodput_floor = _env.get("MXTPU_SLO_GOODPUT_FLOOR")
    if goodput_floor:
        # one unlabeled gauge per process (the goodput accountant is
        # trainer-agnostic), so the objective registers once — the first
        # trainer kind to step wins the race harmlessly
        register(Objective(
            "train-goodput-floor", "gauge_floor",
            metric="mxtpu_goodput_fraction", threshold=goodput_floor,
            description="windowed goodput floor: compute ÷ wall over the "
                        "last MXTPU_GOODPUT_WINDOW_STEPS steps "
                        "(docs/observability.md §Goodput)"), replace=False)


# ---------------------------------------------------------------------------
# /statusz — the "what is wrong right now" page
# ---------------------------------------------------------------------------

_RATE_WINDOW_S = 60.0


def _fresh_verdicts(now, update=False):
    """The cached verdict set when fresh, else a fresh compute. A
    future-stamped cache (clock jump; tests driving synthetic
    timestamps) is stale too, not eternally fresh. ``update`` re-caches
    a fresh compute (the `verdicts()` API path; the statusz path leaves
    the cache alone — a cache hit must never extend its own
    freshness)."""
    lv = _STATE.last_verdicts
    if lv is not None and 0 <= now - lv["ts"] <= 3 * _eval_period_s() + 1.0:
        return lv["verdicts"]
    out = compute_verdicts(now)
    if update:
        # benign swap: racing writers each publish a complete, fresh set
        _STATE.last_verdicts = {"ts": now, "verdicts": out}  # mxlint: gil-atomic — whole-dict swap
    return out


def _series_key(m):
    return m.name + core._render_labels(m.labels)


def _key_rates(now):
    """Windowed key figures over the last `_RATE_WINDOW_S`: per-model rps
    + latency p50/p99, decode tokens/sec + inter-token p99, training step
    rate/p99 + live MFU. Everything here is a ring diff — no locks."""
    out = {"window_s": _RATE_WINDOW_S, "serving": {}, "generate": {},
           "training": {}}
    w = _RATE_WINDOW_S
    for m in core.get_registry().metrics():
        if m.name == "mxtpu_serve_request_seconds":
            row = out["serving"].setdefault(m.labels.get("model", "?"), {})
            wd = m.windowed(w, now)
            if wd:
                row["rps"] = round(wd["rate"], 3)
                row["requests"] = wd["count"]
            p50 = m.windowed_quantile(0.50, w, now)
            p99 = m.windowed_quantile(0.99, w, now)
            row["p50_ms"] = None if p50 is None else round(p50 * 1e3, 3)
            row["p99_ms"] = None if p99 is None else round(p99 * 1e3, 3)
        elif m.name == "mxtpu_serve_queue_depth":
            row = out["serving"].setdefault(m.labels.get("model", "?"), {})
            row["queue_depth"] = m.value
        elif m.name == "mxtpu_serve_generated_tokens_total":
            row = out["generate"].setdefault(m.labels.get("model", "?"), {})
            r = m.windowed_rate(w, now)
            row["tokens_per_sec"] = None if r is None else round(r, 3)
        elif m.name == "mxtpu_serve_intertoken_seconds":
            row = out["generate"].setdefault(m.labels.get("model", "?"), {})
            p99 = m.windowed_quantile(0.99, w, now)
            row["intertoken_p99_ms"] = None if p99 is None \
                else round(p99 * 1e3, 3)
        elif m.name == "mxtpu_serve_kv_occupancy":
            row = out["generate"].setdefault(m.labels.get("model", "?"), {})
            row["kv_occupancy"] = round(m.value, 4)
        elif m.name == "mxtpu_step_seconds":
            row = out["training"].setdefault(m.labels.get("kind", "?"), {})
            wd = m.windowed(w, now)
            if wd:
                row["steps_per_sec"] = round(wd["rate"], 3)
            p99 = m.windowed_quantile(0.99, w, now)
            row["step_p99_s"] = None if p99 is None else round(p99, 4)
        elif m.name == "mxtpu_step_mfu":
            row = out["training"].setdefault(m.labels.get("kind", "?"), {})
            row["mfu"] = round(m.value, 4)
    return out


def _pool_health():
    """Replica-pool health from the published gauges (never the pool's own
    locked describe()): healthy/size + per-replica restart generations."""
    pools = {}
    for m in core.get_registry().metrics():
        if m.name == "mxtpu_serve_pool_healthy":
            pools.setdefault(m.labels.get("model", "?"),
                             {})["healthy"] = int(m.value)
        elif m.name == "mxtpu_serve_pool_size":
            pools.setdefault(m.labels.get("model", "?"),
                             {})["size"] = int(m.value)
        elif m.name == "mxtpu_serve_replica_generation":
            row = pools.setdefault(m.labels.get("model", "?"), {})
            row.setdefault("generations", {})[
                m.labels.get("replica", "?")] = int(m.value)
    return pools


_COMPILE_METRICS = (
    "mxtpu_jit_cache_lookup_total", "mxtpu_jit_cache_miss_total",
    "mxtpu_compile_cache_hit_total", "mxtpu_compile_cache_evict_total",
    "mxtpu_compile_cache_entries", "mxtpu_compile_cache_persist_hit_total",
    "mxtpu_compile_cache_persist_store_total",
    "mxtpu_compile_cache_persist_bad_total")


def _compile_stats():
    """Executable-cache hit/persist figures from the lock-free counters
    (the registry's own stats() takes its lock — off limits here)."""
    out = {}
    for m in core.get_registry().metrics():
        if m.name in _COMPILE_METRICS:
            key = m.name[len("mxtpu_"):]
            out[key] = out.get(key, 0) + m.value
    return out


def _slowest_exemplars(top_n=10):
    """The slowest traced observation per histogram (tail-bucket exemplar),
    worst first: the "render THIS trace" shortlist."""
    rows = []
    for m in core.get_registry().metrics():
        if m.kind != "histogram":
            continue
        best = None
        for ex in m.exemplars().values():
            if best is None or ex["value"] > best["value"]:
                best = ex
        if best is not None:
            rows.append({"metric": _series_key(m),
                         "value": best["value"], "trace": best["trace"],
                         "ts": best["ts"]})
    rows.sort(key=lambda r: -r["value"])
    return rows[:top_n]


def statusz_payload(extra=None):
    """The /statusz document: SLO verdicts + alerts, windowed key rates,
    pool health, compile-cache stats, the memory snapshot and slowest
    exemplars. Signal-safe by construction — lock-free snapshot and ring
    reads only (the mxlint signal-safety checker walks this function), so
    the page answers even when the process is wedged on a library lock."""
    now = time.time()
    core.roll_windows(now)
    payload = {
        "version": 1,
        "ts": now,
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(now)),
        "rank": core.rank(),
        "pid": os.getpid(),
        "generation": core.restart_generation(),
        "slo": {
            "enabled": enabled(),
            "evaluator_running": running(),
            "eval_errors": _STATE.eval_errors,
            "objectives": len(_STATE.objectives),
            "verdicts": _fresh_verdicts(now),
            "alerts": recorder.alerts(),
        },
        "rates": _key_rates(now),
        "pools": _pool_health(),
        "compile_cache": _compile_stats(),
        "memory": memory.snapshot(),
        "training": goodput.statusz_block(),
        "slowest_exemplars": _slowest_exemplars(),
    }
    if extra:
        payload.update(extra)
    return payload


def render_statusz(fmt="json", extra=None):
    """(content_type, body_bytes) for a /statusz reply — shared by the
    ServingServer route and the telemetry exporter."""
    payload = statusz_payload(extra=extra)
    if fmt == "text":
        return ("text/plain; charset=utf-8",
                _render_text(payload).encode())
    return ("application/json",
            (json.dumps(payload, indent=1, default=str) + "\n").encode())


def _render_text(payload):
    """Terse human rendering (the `?format=text` view for a terminal)."""
    lines = ["statusz @ %s rank=%s pid=%s" % (payload["utc"],
                                              payload["rank"],
                                              payload["pid"])]
    slo = payload["slo"]
    lines.append("slo: enabled=%s evaluator=%s objectives=%d"
                 % (slo["enabled"], slo["evaluator_running"],
                    slo["objectives"]))
    for v in slo["verdicts"]:
        state = "NO_DATA" if v["no_data"] else (
            "BREACH" if v["page"] else ("ticket" if v["ticket"] else "ok"))
        lines.append(
            "  [%-7s] %s burn=%.2f budget_left=%s value=%s thr=%s%s"
            % (state, v["slo"], v["burn_rate"],
               "-" if v["budget_remaining"] is None
               else "%.2f" % v["budget_remaining"],
               "-" if v["value"] is None else "%.4g" % v["value"],
               "-" if v["threshold"] is None else "%g" % v["threshold"],
               " trace=%s" % v["exemplar_trace"]
               if v["exemplar_trace"] else ""))
    for name, fields in sorted(payload["rates"]["serving"].items()):
        lines.append("  serve %s: %s" % (name, fields))
    for name, fields in sorted(payload["rates"]["generate"].items()):
        lines.append("  decode %s: %s" % (name, fields))
    for kind, fields in sorted(payload["rates"]["training"].items()):
        lines.append("  train %s: %s" % (kind, fields))
    tr = payload.get("training") or {}
    if tr.get("window_steps"):
        lines.append("goodput: frac=%s over %d steps top_stall=%s (%.4gs)"
                     % (tr.get("goodput_fraction"), tr["window_steps"],
                        tr.get("top_stall_phase"),
                        tr.get("top_stall_seconds", 0.0)))
    for name, pool in sorted(payload["pools"].items()):
        lines.append("  pool %s: %s" % (name, pool))
    if payload["compile_cache"]:
        lines.append("compile: %s" % payload["compile_cache"])
    proc = (payload["memory"] or {}).get("process") or {}
    lines.append("memory: rss=%s vmhwm=%s" % (proc.get("rss"),
                                              proc.get("vmhwm")))
    for a in slo["alerts"]:
        lines.append("alert: %s %s" % (a.get("event"), a.get("fields")))
    for ex in payload["slowest_exemplars"][:5]:
        lines.append("slow: %.4gs %s trace=%s"
                     % (ex["value"], ex["metric"], ex["trace"]))
    return "\n".join(lines) + "\n"
