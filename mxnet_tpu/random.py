"""Global RNG state.

The reference keeps per-device cuRAND/mt19937 resource states handed to ops via
ResourceManager (reference: src/resource.cc, include/mxnet/resource.h:38-46).
TPU-native design: a single stateless threefry key chain — every random op
consumes one fresh subkey split off the global chain, so eager ops are
reproducible under `seed()` while traced graphs receive the key as a runtime
input (keeping compiled executables deterministic functions of their inputs).
"""
from __future__ import annotations

import threading

import numpy as _np

__all__ = ["seed", "next_key", "current_seed", "get_state", "set_state"]

_state = threading.local()
_DEFAULT_SEED = 0


def _get():
    if not hasattr(_state, "key"):
        import jax

        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
        _state.seed_val = _DEFAULT_SEED
    return _state


def seed(seed_state, ctx="all"):
    """Seed the global RNG (reference: python/mxnet/random.py:38 mx.random.seed).

    `ctx` accepted for API parity; with a single stateless chain the seed is
    global (per-device streams are derived by folding device ids in sharded
    code paths)."""
    import jax

    st = _get()
    st.key = jax.random.PRNGKey(int(seed_state))
    st.seed_val = int(seed_state)
    st.staged_ctr = 0


def current_seed():
    return _get().seed_val


def get_state():
    """Snapshot the global key chain as plain host data (for checkpoints —
    parallel/resilience.py captures this so a resumed run continues the
    SAME random stream it would have seen uninterrupted: dropout masks,
    shuffles and init draws replay identically after auto-resume)."""
    import jax

    st = _get()
    key = st.key
    if jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key):
        key = jax.random.key_data(key)  # typed keys serialize via raw data
    return {"seed": st.seed_val,
            "key": _np.asarray(key).tolist(),
            "staged_ctr": getattr(st, "staged_ctr", 0)}


def set_state(state):
    """Restore a get_state() snapshot (checkpoint resume path)."""
    import jax
    import jax.numpy as jnp

    st = _get()
    st.seed_val = int(state["seed"])
    key = jnp.asarray(_np.asarray(state["key"], dtype=_np.uint32))
    # rewrap through the typed-key API when the snapshot came from one
    if jax.dtypes.issubdtype(st.key.dtype, jax.dtypes.prng_key):
        key = jax.random.wrap_key_data(key)
    st.key = key
    st.staged_ctr = int(state.get("staged_ctr", 0))


def next_key():
    """Split one subkey off the global chain (consumed by a single random op).

    Under graph tracing (CachedOp/Symbol executor) a *trace key* is active:
    subkeys are derived deterministically from it by fold_in(counter), so the
    compiled executable takes the key as a runtime input and stays a pure
    function — fresh randomness per call, reproducible under seed()."""
    import jax

    st = _get()
    trace = getattr(st, "trace", None)
    if trace is not None:
        key = jax.random.fold_in(trace[0], trace[1])
        trace[1] += 1
        return key
    new_key, sub = jax.random.split(st.key)
    if isinstance(new_key, jax.core.Tracer):
        # An eager op is being traced by an OUTER jit with no trace key
        # pushed (e.g. a user jits an eager forward containing Dropout):
        # under omnistaging the split is staged, and persisting its tracer
        # result into the global chain poisons every later trace with a
        # leaked-tracer error. Keep the chain's concrete position and
        # derive in-trace keys by folding a local counter instead (still
        # distinct per draw within the trace, reproducible under seed()).
        ctr = getattr(st, "staged_ctr", 0)
        st.staged_ctr = ctr + 1
        if not getattr(st, "staged_warned", False):
            st.staged_warned = True
            import logging

            logging.getLogger("mxnet_tpu").warning(
                "random op traced under an outer jax.jit without a trace "
                "key: the drawn key is baked into the executable as a "
                "constant, so every call of the jitted function reuses the "
                "same randomness. Use CachedOp/hybridize (which feeds the "
                "key as a runtime input) for fresh draws per call.")
        return jax.random.fold_in(st.key, ctr)
    st.key = new_key
    return sub


def push_trace_key(key):
    st = _get()
    prev = getattr(st, "trace", None)
    st.trace = [key, 0]
    return prev


def pop_trace_key(prev=None):
    _get().trace = prev


def np_random():
    """numpy Generator used by host-side shufflers (data pipeline)."""
    return _np.random.default_rng(_get().seed_val)
