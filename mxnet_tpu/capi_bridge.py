"""Python bridge for the imperative flat C ABI (libmxtpu_capi.so).

Reference: src/c_api/c_api_ndarray.cc (`MXImperativeInvoke` :132) +
c_api.cc NDArray create/copy/shape entry points + autograd control
(c_api_ndarray.cc:257-281). The C layer (lib/src_capi/c_api.cc) owns the
handle lifetime and marshals raw bytes/strings; every NDArray/op/autograd
semantic lives here. Each `_capi_*` function takes/returns only
plain-Python values (bytes, tuples, ints) plus NDArray objects whose
references the C side holds.

Attribute strings: the reference parses op params from strings via
dmlc::Parameter reflection; here `ast.literal_eval` covers the same
surface (numbers, bools, tuples), with plain words (e.g. pool_type
values) passing through as strings.
"""
from __future__ import annotations

import ast

import numpy as _np

from .base import MXNetError

# (importing this module always executes the package __init__ first, which
# re-asserts an explicit JAX_PLATFORMS=cpu choice — including in an
# EMBEDDED interpreter booted by a plain-C host where no conftest runs)

# the reference's dtype enum (python/mxnet/base.py _DTYPE_MX_TO_NP order,
# mirrored by include/mxnet/ndarray.h)
_DTYPE_MX_TO_NP = {0: _np.float32, 1: _np.float64, 2: _np.float16,
                   3: _np.uint8, 4: _np.int32, 5: _np.int8, 6: _np.int64}
_DTYPE_NP_TO_MX = {_np.dtype(v).name: k for k, v in _DTYPE_MX_TO_NP.items()}

_DEVTYPE = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
_DEVTYPE_TO_INT = {v: k for k, v in _DEVTYPE.items()}


def _ctx(dev_type, dev_id):
    from .context import Context

    return Context(_DEVTYPE.get(int(dev_type), "cpu"), int(dev_id))


def _capi_nd_create(shape, dev_type, dev_id, dtype):
    from . import ndarray as nd

    np_dt = _DTYPE_MX_TO_NP.get(int(dtype))
    if np_dt is None:
        raise MXNetError("unsupported dtype enum %d" % dtype)
    return nd.zeros(tuple(int(s) for s in shape),
                    ctx=_ctx(dev_type, dev_id), dtype=np_dt)


def _capi_nd_sync_copy_from(arr, raw):
    expected = int(_np.prod(arr.shape)) if arr.shape else 1
    host = _np.frombuffer(bytes(raw), dtype=arr.dtype)
    if host.size != expected:
        raise MXNetError("SyncCopyFromCPU: got %d elements, NDArray holds "
                         "%d" % (host.size, expected))
    from . import ndarray as nd

    arr._set_data(nd.array(host.reshape(arr.shape), ctx=arr.context,
                           dtype=arr.dtype)._data)


def _capi_nd_sync_copy_to(arr):
    return _np.ascontiguousarray(arr.asnumpy()).tobytes()


def _capi_nd_shape(arr):
    return tuple(int(d) for d in arr.shape)


def _capi_nd_dtype(arr):
    name = _np.dtype(arr.dtype).name
    if name not in _DTYPE_NP_TO_MX:
        raise MXNetError("dtype %s has no reference enum value" % name)
    return _DTYPE_NP_TO_MX[name]


def _capi_nd_context(arr):
    ctx = arr.context
    return _DEVTYPE_TO_INT.get(ctx.device_type, 1), int(ctx.device_id)


def _capi_nd_itemsize(arr):
    """Element byte width — authoritative in ONE place (the C side must
    not duplicate the dtype-enum table)."""
    return int(_np.dtype(arr.dtype).itemsize)


def _capi_list_ops():
    from . import ops

    return sorted(ops.list_ops())


def _parse_attr(val):
    """Reference semantics: op params arrive as strings and are parsed by
    dmlc::Parameter; literal_eval covers numbers/bools/tuples, anything
    else stays a string (enum-valued params like pool_type='max')."""
    s = val.decode() if isinstance(val, bytes) else val
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def _parse_attrs(keys, vals):
    """One parsing site for every C-ABI (keys, vals) string-attr pair
    (invoke, symbol creation, iterator creation)."""
    return {k.decode() if isinstance(k, bytes) else k: _parse_attr(v)
            for k, v in zip(keys, vals)}


def _capi_invoke(op_name, inputs, keys, vals, outs=None):
    """MXImperativeInvoke core: op by name, NDArray inputs, string attrs.
    With `outs` (the reference's in-place contract) results are written
    into the given arrays; returns a list of output NDArrays either way."""
    from .ndarray import invoke

    attrs = _parse_attrs(keys, vals)
    out = invoke(op_name, tuple(inputs), attrs,
                 out=list(outs) if outs is not None else None)
    return list(out) if isinstance(out, (list, tuple)) else [out]


def _capi_autograd_set_recording(flag):
    from . import autograd

    return 1 if autograd.set_recording(bool(flag)) else 0


def _capi_autograd_set_training(flag):
    from . import autograd

    return 1 if autograd.set_training(bool(flag)) else 0


_GRAD_REQ = {0: "null", 1: "write", 2: "add"}


def _capi_mark_variables(variables, reqs, gradients):
    from . import autograd

    req_names = [_GRAD_REQ.get(int(r), "write") for r in reqs]
    autograd.mark_variables(list(variables), list(gradients), req_names)


def _capi_backward(outputs, ograds, retain_graph):
    from . import autograd

    heads = list(outputs)
    head_grads = None if ograds is None else list(ograds)
    autograd.backward(heads, head_grads, retain_graph=bool(retain_graph))


def _capi_get_grad(arr):
    return arr.grad  # None when no gradient buffer is attached


def _capi_nd_slice(arr, begin, end):
    begin, end = int(begin), int(end)
    n = arr.shape[0] if arr.shape else 0
    # reference MXNDArraySlice CHECK-fails on bad ranges; numpy-style
    # clamping would hand a C host silently short data with rc=0
    if not 0 <= begin < end <= n:
        raise MXNetError("MXNDArraySlice: invalid range [%d, %d) for "
                         "axis-0 size %d" % (begin, end, n))
    return arr[begin:end]


def _capi_nd_at(arr, idx):
    idx = int(idx)
    n = arr.shape[0] if arr.shape else 0
    if not 0 <= idx < n:
        raise MXNetError("MXNDArrayAt: index %d out of range for axis-0 "
                         "size %d" % (idx, n))
    return arr[idx]


def _capi_nd_reshape(arr, dims):
    return arr.reshape(tuple(int(d) for d in dims))


def _capi_nd_storage_type(arr):
    # reference enum: -1 undefined, 0 default (dense), 1 row_sparse, 2 csr
    st = getattr(arr, "stype", "default")
    return {"default": 0, "row_sparse": 1, "csr": 2}.get(st, 0)


def _capi_nd_wait_to_read(arr):
    arr.wait_to_read()


def _capi_wait_all():
    from . import ndarray as nd

    nd.waitall()


# -- symbol section (reference: c_api_symbolic.cc) --------------------------
# A C SymbolHandle owns a _SymRec. CreateAtomicSymbol makes a node with no
# inputs (sym=None); Compose instantiates it through the generated mx.sym
# op function — after that every symbol fn operates on .sym.


class _SymRec:
    __slots__ = ("op", "attrs", "sym")

    def __init__(self, op=None, attrs=None, sym=None):
        self.op = op
        self.attrs = attrs or {}
        self.sym = sym

    def require(self):
        if self.sym is None:
            raise ValueError(
                "symbol %r has not been composed yet (MXSymbolCompose "
                "binds its inputs, reference c_api_symbolic.cc:481)"
                % (self.op,))
        return self.sym


def _capi_sym_create_variable(name):
    from . import symbol as sym_mod

    return _SymRec(sym=sym_mod.Variable(name))


def _capi_sym_create_atomic(op_name, keys, vals):
    return _SymRec(op=op_name, attrs=_parse_attrs(keys, vals))


def _capi_sym_compose(rec, name, keys, args):
    from . import symbol as sym_mod

    syms = [a.require() for a in args]
    if keys and len(keys) != len(syms):
        raise ValueError(
            "MXSymbolCompose: %d keys for %d inputs (keys must be "
            "all-positional or one per input)" % (len(keys), len(syms)))
    kwargs = dict(rec.attrs)
    if name:
        kwargs["name"] = name
    fn = getattr(sym_mod, rec.op)
    if keys:
        kwargs.update({k.decode() if isinstance(k, bytes) else k: s
                       for k, s in zip(keys, syms)})
        rec.sym = fn(**kwargs)
    else:
        rec.sym = fn(*syms, **kwargs)


def _capi_sym_copy(rec):
    return _SymRec(op=rec.op, attrs=dict(rec.attrs), sym=rec.require())


def _capi_sym_group(recs):
    from . import symbol as sym_mod

    return _SymRec(sym=sym_mod.Group([r.require() for r in recs]))


def _capi_sym_internals(rec):
    return _SymRec(sym=rec.require().get_internals())


def _capi_sym_get_output(rec, index):
    return _SymRec(sym=rec.require()[int(index)])


def _capi_sym_list_arguments(rec):
    return list(rec.require().list_arguments())


def _capi_sym_list_outputs(rec):
    return list(rec.require().list_outputs())


def _capi_sym_list_aux(rec):
    return list(rec.require().list_auxiliary_states())


def _capi_sym_tojson(rec):
    return rec.require().tojson()


def _capi_sym_from_json(js):
    from .symbol import symbol as sym_impl

    return _SymRec(sym=sym_impl.load_json(
        js.decode() if isinstance(js, bytes) else js))


def _capi_sym_infer_shape(rec, keys, shapes, partial):
    """keys + per-key shape tuples -> (arg, out, aux shape lists,
    complete). Unknown-by-position keys ('' entries) follow
    list_arguments order like the reference's positional CSR form."""
    s = rec.require()
    kwargs = {}
    names = s.list_arguments()
    for i, (k, shp) in enumerate(zip(keys, shapes)):
        k = k.decode() if isinstance(k, bytes) else k
        kwargs[k if k else names[i]] = tuple(int(d) for d in shp)
    fn = s.infer_shape_partial if partial else s.infer_shape
    try:
        arg, out, aux = fn(**kwargs)
    except Exception:
        if partial:
            raise
        # under-specified shapes are NOT an error in the reference C API
        # (c_api_symbolic.cc): it succeeds with *complete = 0
        return ([], [], [], 0)
    complete = arg is not None and all(
        x is not None and all(d > 0 for d in x) for x in (arg + out + aux))
    return (arg or [], out or [], aux or [], 1 if complete else 0)


def _capi_executor_bind(rec, dev_type, dev_id, in_args, arg_grads,
                        grad_reqs, aux_states):
    s = rec.require()
    ctx = _ctx(dev_type, dev_id)
    names = s.list_arguments()
    args = dict(zip(names, in_args))
    args_grad = {n: g for n, g in zip(names, arg_grads) if g is not None}
    grad_req = {n: _GRAD_REQ.get(int(r), "write")
                for n, r in zip(names, grad_reqs)}
    return s.bind(ctx, args=args, args_grad=args_grad or None,
                  grad_req=grad_req, aux_states=list(aux_states) or None)


def _capi_executor_forward(executor, is_train):
    executor.forward(is_train=bool(is_train))


def _capi_executor_outputs(executor):
    return list(executor.outputs)


def _capi_executor_backward(executor, head_grads):
    executor.backward(out_grads=list(head_grads) if head_grads else None)


def _capi_executor_arg_grads(executor):
    return list(executor.grad_arrays)


def _capi_sym_get_name(rec):
    name = rec.require().name
    return (name or "", 1 if name is not None else 0)


def _capi_sym_get_attr(rec, key):
    key = key.decode() if isinstance(key, bytes) else key
    val = rec.require().attr(key)
    return (str(val) if val is not None else "",
            1 if val is not None else 0)


def _capi_sym_set_attr(rec, key, val):
    from .symbol.symbol import _wrap_attr_keys

    key = key.decode() if isinstance(key, bytes) else key
    val = val.decode() if isinstance(val, bytes) else val
    s = rec.require()
    # user attrs store __key__-wrapped (they must never reach op kwargs)
    # and as RAW strings — the reference MXSymbolSetAttr contract; no
    # _parse_attr here or set/get round-trips would re-format values
    s._outputs[0][0].attrs.update(_wrap_attr_keys({key: val}))


def _unwrap_attr_key(k):
    return k[2:-2] if k.startswith("__") and k.endswith("__") and len(k) > 4 \
        else k


def _capi_sym_list_attr(rec, shallow):
    """Flattened [k1, v1, k2, v2, ...]; deep form prefixes descendant
    node names as 'name$key' (reference c_api_symbolic.cc ListAttr).
    User attrs present themselves under their unwrapped names, the form
    the reference stores and the C host wrote."""
    s = rec.require()
    pairs = []
    if shallow:
        node = s._outputs[0][0]
        for k, v in sorted(node.attrs.items()):
            pairs += [_unwrap_attr_key(str(k)), str(v)]
    else:
        for name, attrs in sorted(s.attr_dict().items()):
            for k, v in sorted(attrs.items()):
                pairs += ["%s$%s" % (name, _unwrap_attr_key(str(k))),
                          str(v)]
    return pairs


def _capi_atomic_symbol_info(op_name):
    """(description, arg_names, arg_type_infos, arg_descriptions,
    key_var_num_args) derived from the generated op function's
    caller-facing signature (reference reads dmlc::Parameter reflection;
    here the signature IS the parameter surface)."""
    import inspect

    from . import ndarray as nd

    op_name = op_name.decode() if isinstance(op_name, bytes) else op_name
    from . import ops

    opdef = ops.get(op_name)
    fn = opdef.fn  # the raw op fn carries the real parameter surface
    doc = (getattr(getattr(nd, op_name, None), "__doc__", None)
           or fn.__doc__ or "").strip()
    names, types = [], []
    has_varargs = False
    try:
        params = list(inspect.signature(fn).parameters.values())
        if opdef.needs_rng and params:
            params = params[1:]  # the PRNG key is runtime-injected
        for p in params:
            if p.kind == inspect.Parameter.VAR_POSITIONAL:
                has_varargs = True
                continue
            if p.kind == inspect.Parameter.VAR_KEYWORD:
                continue
            names.append(p.name)
            types.append("" if p.default is inspect.Parameter.empty
                         else "optional, default=%r" % (p.default,))
    except (TypeError, ValueError):
        pass
    # the reference's key_var_num_args is the COUNT parameter's name
    # (hosts pass {num_args: N} when composing variadic ops), not the
    # *args name itself
    var_args = ""
    if has_varargs:
        var_args = "num_args" if "num_args" in names else ""
    return (doc, names, types, [""] * len(names), var_args)


# -- kvstore section (reference: c_api.cc MXKVStore*) -----------------------

def _capi_kv_create(name):
    from . import kvstore

    return kvstore.create(name.decode() if isinstance(name, bytes) else name)


def _capi_kv_init(kv, keys, vals):
    kv.init(list(keys), list(vals))


def _capi_kv_push(kv, keys, vals, priority):
    kv.push(list(keys), list(vals), priority=int(priority))


def _capi_kv_pull(kv, keys, outs, priority):
    kv.pull(list(keys), out=list(outs), priority=int(priority))


def _capi_kv_type(kv):
    return kv.type


def _capi_kv_rank(kv):
    return int(kv.rank)


def _capi_kv_group_size(kv):
    return int(kv.num_workers)


def _capi_kv_barrier(kv):
    kv.barrier()


def _capi_kv_set_updater(kv, fn_addr, handle_addr):
    """Install a C updater callback: `fn_addr` is the C function pointer
    void (*)(int key, NDArrayHandle recv, NDArrayHandle local, void*).
    The trampoline materializes fresh C handles for each call; the C side
    frees them via MXNDArrayFree per the reference contract."""
    import ctypes

    from .lib import native

    CB = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                          ctypes.c_void_p, ctypes.c_void_p)
    cb = CB(fn_addr)
    lib = native.get_capi()
    lib.mxtpu_capi_wrap_handle.restype = ctypes.c_void_p
    lib.mxtpu_capi_wrap_handle.argtypes = [ctypes.py_object]
    lib.MXNDArrayFree.argtypes = [ctypes.c_void_p]

    def updater(key, recv, local):
        # hand the C callback real NDArrayHandles: heap structs whose
        # first member is the PyObject*, made on the C side to keep one
        # allocator for new/delete. Ownership follows the reference
        # MXKVStoreUpdater contract: the UPDATER frees recv and local
        # (c_api.h: "It's this updater's responsibility to delete recv
        # and local") — the trampoline must NOT free them too.
        hr = lib.mxtpu_capi_wrap_handle(ctypes.py_object(recv))
        hl = lib.mxtpu_capi_wrap_handle(ctypes.py_object(local))
        cb(int(key), hr, hl, handle_addr)

    kv._capi_updater = updater  # keep the CFUNCTYPE alive
    kv.set_updater(updater)


# -- data-iterator section (reference: c_api.cc MXDataIter*) ----------------
# A DataIterCreator handle is an interned iterator-name string (the same
# scheme as op creators); an iterator handle owns the Python DataIter
# plus its current batch.

# the file-fed iterators (the reference's C creators are the compiled
# file-based ones; NDArrayIter is a Python-side construct there too)
_DATA_ITERS = ("MNISTIter", "CSVIter", "LibSVMIter", "ImageRecordIter")


def _capi_list_data_iters():
    return list(_DATA_ITERS)


def _capi_iter_create(name, keys, vals):
    from . import io

    name = name.decode() if isinstance(name, bytes) else name
    if name not in _DATA_ITERS:
        raise ValueError("unknown data iter %r (have %s)"
                         % (name, ", ".join(_DATA_ITERS)))
    it = getattr(io, name)(**_parse_attrs(keys, vals))
    return {"iter": iter(it), "src": it, "batch": None}


def _capi_iter_next(state):
    try:
        state["batch"] = next(state["iter"])
        return 1
    except StopIteration:
        state["batch"] = None
        return 0


def _capi_iter_before_first(state):
    state["src"].reset()
    state["iter"] = iter(state["src"])
    state["batch"] = None


def _batch(state):
    b = state["batch"]
    if b is None:
        raise ValueError("no current batch: call MXDataIterNext first")
    return b


def _capi_iter_get_data(state):
    return _batch(state).data[0]


def _capi_iter_get_label(state):
    b = _batch(state)
    if not b.label:
        raise ValueError("batch carries no label")
    return b.label[0]


def _capi_iter_get_pad(state):
    return int(_batch(state).pad or 0)


# -- NDArray save/load (reference: c_api.cc MXNDArraySave/Load) -------------

def _capi_nd_save(fname, arrays, keys):
    from . import ndarray as nd

    fname = fname.decode() if isinstance(fname, bytes) else fname
    if keys:
        nd.save(fname, {k.decode() if isinstance(k, bytes) else k: a
                        for k, a in zip(keys, arrays)})
    else:
        nd.save(fname, list(arrays))


def _capi_nd_load(fname):
    from . import ndarray as nd

    fname = fname.decode() if isinstance(fname, bytes) else fname
    data = nd.load(fname)
    if isinstance(data, dict):
        names = list(data.keys())
        return names, [data[n] for n in names]
    return [], list(data)


def _capi_version():
    from . import __version__

    parts = (str(__version__).split("+")[0].split("."))
    nums = [int("".join(c for c in p if c.isdigit()) or 0) for p in parts[:3]]
    while len(nums) < 3:
        nums.append(0)
    return nums[0] * 10000 + nums[1] * 100 + nums[2]
