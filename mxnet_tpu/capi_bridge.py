"""Python bridge for the imperative flat C ABI (libmxtpu_capi.so).

Reference: src/c_api/c_api_ndarray.cc (`MXImperativeInvoke` :132) +
c_api.cc NDArray create/copy/shape entry points + autograd control
(c_api_ndarray.cc:257-281). The C layer (lib/src_capi/c_api.cc) owns the
handle lifetime and marshals raw bytes/strings; every NDArray/op/autograd
semantic lives here. Each `_capi_*` function takes/returns only
plain-Python values (bytes, tuples, ints) plus NDArray objects whose
references the C side holds.

Attribute strings: the reference parses op params from strings via
dmlc::Parameter reflection; here `ast.literal_eval` covers the same
surface (numbers, bools, tuples), with plain words (e.g. pool_type
values) passing through as strings.
"""
from __future__ import annotations

import ast
import os

import numpy as _np

from .base import MXNetError

# A sitecustomize PJRT hook may force-override jax_platforms at interpreter
# start (dialing accelerator hardware); in an EMBEDDED interpreter booted by
# a plain-C host there is no conftest to re-assert the env's explicit
# choice, so honor it here before any jax computation runs.
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

# the reference's dtype enum (python/mxnet/base.py _DTYPE_MX_TO_NP order,
# mirrored by include/mxnet/ndarray.h)
_DTYPE_MX_TO_NP = {0: _np.float32, 1: _np.float64, 2: _np.float16,
                   3: _np.uint8, 4: _np.int32, 5: _np.int8, 6: _np.int64}
_DTYPE_NP_TO_MX = {_np.dtype(v).name: k for k, v in _DTYPE_MX_TO_NP.items()}

_DEVTYPE = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
_DEVTYPE_TO_INT = {v: k for k, v in _DEVTYPE.items()}


def _ctx(dev_type, dev_id):
    from .context import Context

    return Context(_DEVTYPE.get(int(dev_type), "cpu"), int(dev_id))


def _capi_nd_create(shape, dev_type, dev_id, dtype):
    from . import ndarray as nd

    np_dt = _DTYPE_MX_TO_NP.get(int(dtype))
    if np_dt is None:
        raise MXNetError("unsupported dtype enum %d" % dtype)
    return nd.zeros(tuple(int(s) for s in shape),
                    ctx=_ctx(dev_type, dev_id), dtype=np_dt)


def _capi_nd_sync_copy_from(arr, raw):
    expected = int(_np.prod(arr.shape)) if arr.shape else 1
    host = _np.frombuffer(bytes(raw), dtype=arr.dtype)
    if host.size != expected:
        raise MXNetError("SyncCopyFromCPU: got %d elements, NDArray holds "
                         "%d" % (host.size, expected))
    from . import ndarray as nd

    arr._set_data(nd.array(host.reshape(arr.shape), ctx=arr.context,
                           dtype=arr.dtype)._data)


def _capi_nd_sync_copy_to(arr):
    return _np.ascontiguousarray(arr.asnumpy()).tobytes()


def _capi_nd_shape(arr):
    return tuple(int(d) for d in arr.shape)


def _capi_nd_dtype(arr):
    name = _np.dtype(arr.dtype).name
    if name not in _DTYPE_NP_TO_MX:
        raise MXNetError("dtype %s has no reference enum value" % name)
    return _DTYPE_NP_TO_MX[name]


def _capi_nd_context(arr):
    ctx = arr.context
    return _DEVTYPE_TO_INT.get(ctx.device_type, 1), int(ctx.device_id)


def _capi_nd_itemsize(arr):
    """Element byte width — authoritative in ONE place (the C side must
    not duplicate the dtype-enum table)."""
    return int(_np.dtype(arr.dtype).itemsize)


def _capi_list_ops():
    from . import ops

    return sorted(ops.list_ops())


def _parse_attr(val):
    """Reference semantics: op params arrive as strings and are parsed by
    dmlc::Parameter; literal_eval covers numbers/bools/tuples, anything
    else stays a string (enum-valued params like pool_type='max')."""
    s = val.decode() if isinstance(val, bytes) else val
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def _capi_invoke(op_name, inputs, keys, vals, outs=None):
    """MXImperativeInvoke core: op by name, NDArray inputs, string attrs.
    With `outs` (the reference's in-place contract) results are written
    into the given arrays; returns a list of output NDArrays either way."""
    from .ndarray import invoke

    attrs = {k.decode() if isinstance(k, bytes) else k: _parse_attr(v)
             for k, v in zip(keys, vals)}
    out = invoke(op_name, tuple(inputs), attrs,
                 out=list(outs) if outs is not None else None)
    return list(out) if isinstance(out, (list, tuple)) else [out]


def _capi_autograd_set_recording(flag):
    from . import autograd

    return 1 if autograd.set_recording(bool(flag)) else 0


def _capi_autograd_set_training(flag):
    from . import autograd

    return 1 if autograd.set_training(bool(flag)) else 0


_GRAD_REQ = {0: "null", 1: "write", 2: "add"}


def _capi_mark_variables(variables, reqs, gradients):
    from . import autograd

    req_names = [_GRAD_REQ.get(int(r), "write") for r in reqs]
    autograd.mark_variables(list(variables), list(gradients), req_names)


def _capi_backward(outputs, ograds, retain_graph):
    from . import autograd

    heads = list(outputs)
    head_grads = None if ograds is None else list(ograds)
    autograd.backward(heads, head_grads, retain_graph=bool(retain_graph))


def _capi_get_grad(arr):
    return arr.grad  # None when no gradient buffer is attached


def _capi_version():
    from . import __version__

    parts = (str(__version__).split("+")[0].split("."))
    nums = [int("".join(c for c in p if c.isdigit()) or 0) for p in parts[:3]]
    while len(nums) < 3:
        nums.append(0)
    return nums[0] * 10000 + nums[1] * 100 + nums[2]
