"""Parameter-server server-role compatibility (reference:
python/mxnet/kvstore_server.py — the main loop a `DMLC_ROLE=server`
process runs, receiving ZPush/ZPull and applying the pickled optimizer
server-side).

Architecture note: this framework replaces the parameter server with XLA
collectives inside the compiled step (SURVEY §5.8 — kvstore 'dist' is an
allreduce over the jax.distributed rendezvous). Every process is a worker;
there are no server processes to run, so `run()` returns immediately
after logging what replaced it, and `_init_kvstore_server_module()` is a
no-op for workers — launch scripts written for the reference (which start
N servers alongside N workers) keep working: the server ranks simply exit
cleanly instead of blocking in a receive loop."""
from __future__ import annotations

import logging
import os

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer:
    """reference: kvstore_server.py KVStoreServer."""

    def __init__(self, kvstore):
        self.kvstore = kvstore

    def _controller(self):
        """reference: the cmd-0 handler installs a pickled optimizer; our
        store applies optimizers worker-side (set_optimizer), so the
        controller just forwards."""

        def server_controller(cmd_id, cmd_body, _=None):
            if cmd_id == 0:
                import pickle

                self.kvstore.set_optimizer(pickle.loads(cmd_body))
            else:
                logging.warning("kvstore server: unknown command (%s)",
                                cmd_id)

        return server_controller

    def run(self):
        logging.info(
            "kvstore server role: no PS loop to run — gradients aggregate "
            "as XLA collectives inside the compiled step (kvstore 'dist' "
            "over the jax.distributed rendezvous); exiting cleanly")


def _init_kvstore_server_module():
    """reference: kvstore_server.py:79 — block in the server loop when this
    process was launched with a server role."""
    role = os.environ.get("DMLC_ROLE", "worker")
    if role == "server":
        # no kvstore is created: a store would join the worker rendezvous,
        # and there is no PS traffic to serve — log the architecture note
        # (KVStoreServer.run) and exit cleanly
        KVStoreServer(None).run()
        return True
    if role == "scheduler":
        # the jax.distributed coordinator plays the scheduler; rank 0's
        # worker process hosts it, so a dedicated scheduler just exits
        logging.info("kvstore scheduler role: coordinator is hosted by "
                     "rank 0's worker process; exiting cleanly")
        return True
    return False


def _maybe_exit_non_worker():
    """Called from mxnet_tpu/__init__ (the reference calls
    _init_kvstore_server_module at import): a reference launch script's
    server/scheduler ranks never execute the training script body — they
    block in the PS loop. Here they exit(0) instead, keeping the worker
    world size correct."""
    if _init_kvstore_server_module():
        raise SystemExit(0)
