"""RecordIO: binary record pack/read.

Reference: python/mxnet/recordio.py (MXRecordIO/MXIndexedRecordIO, pack/unpack,
IRHeader) over dmlc-core's recordio format. This is a from-scratch
implementation of the same on-disk format (magic-framed, 4-byte aligned
records; image records carry an IRHeader) so datasets packed by the reference
tooling (tools/im2rec) read unchanged. A C++ accelerated reader is provided in
native/ (used automatically when built) for the hot data-pipeline path."""
from __future__ import annotations

import collections
import os
import struct

import numpy as _np

from . import env as _env
from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_CFLAG_BITS = 29
_LEN_MASK = (1 << _CFLAG_BITS) - 1


class MXRecordIO:
    """Sequential record reader/writer (reference: recordio.py:36)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.is_open = False
        self.open()

    def open(self):
        from .lib import native as _native

        self.handle = None
        self._native = None
        if self.flag == "w":
            self.writable = True
            if _native.available() and not _env.get("MXTPU_PY_RECORDIO"):
                self._native = _native.RecordWriter(self.uri)
            else:
                self.handle = open(self.uri, "wb")
        elif self.flag == "r":
            self.writable = False
            if _native.available() and not _env.get("MXTPU_PY_RECORDIO"):
                self._native = _native.RecordReader(self.uri)
            else:
                self.handle = open(self.uri, "rb")
        else:
            raise MXNetError("Invalid flag %s" % self.flag)
        self.pid = os.getpid()
        self.is_open = True

    def close(self):
        if self.is_open:
            if self._native is not None:
                self._native.close()
                self._native = None
            if self.handle is not None:
                self.handle.close()
                self.handle = None
            self.is_open = False
            self.pid = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("handle", None)
        d.pop("_native", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def reset(self):
        if self._native is not None and not self.writable:
            self._native.reset()
            return
        self.close()
        self.open()

    def tell(self):
        if self._native is not None:
            return self._native.tell()
        return self.handle.tell()

    def write(self, buf):
        assert self.writable
        if self._native is not None:
            self._native.write(bytes(buf))
            return
        lrec = len(buf) & _LEN_MASK
        self.handle.write(struct.pack("<II", _MAGIC, lrec))
        self.handle.write(buf)
        pad = (-len(buf)) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        if self._native is not None:
            return self._native.read()
        head = self.handle.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _MAGIC:
            raise MXNetError("invalid record magic 0x%x at offset %d"
                             % (magic, self.handle.tell() - 8))
        length = lrec & _LEN_MASK
        buf = self.handle.read(length)
        pad = (-length) % 4
        if pad:
            self.handle.read(pad)
        return buf


class MXIndexedRecordIO(MXRecordIO):
    """Random-access records via an .idx file (reference: recordio.py:92)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if self.is_open and self.writable:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write("%s\t%d\n" % (str(k), self.idx[k]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        if self._native is not None:
            # a subsequent read() must serve this position (same contract as
            # the python handle.seek path)
            self._pending_pos = self.idx[idx]
        else:
            self.handle.seek(self.idx[idx])

    def read(self):
        if self._native is not None and not self.writable \
                and getattr(self, "_pending_pos", None) is not None:
            pos, self._pending_pos = self._pending_pos, None
            return self._native.read_at(pos)
        return super().read()

    def read_idx(self, idx):
        if self._native is not None:
            self._pending_pos = None
            return self._native.read_at(self.idx[idx])
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = collections.namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack an IRHeader + payload (reference: recordio.py pack). flag>0 means
    `flag` float labels follow the fixed header."""
    header = IRHeader(*header)
    if isinstance(header.label, (list, tuple, _np.ndarray)):
        label = _np.asarray(header.label, dtype=_np.float32)
        header = header._replace(flag=label.size, label=0)
        s = label.tobytes() + s
    return struct.pack(_IR_FORMAT, header.flag, float(header.label),
                       header.id, header.id2) + s


def unpack(s):
    """Unpack a record into (IRHeader, payload) (reference: recordio.py unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = _np.frombuffer(s[:header.flag * 4], dtype=_np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an image array and pack (reference: recordio.py pack_img)."""
    from . import image

    buf = image.imencode(img, quality=quality, fmt=img_fmt)
    return pack(header, buf)


def unpack_img(s, iscolor=-1):
    """Unpack and decode an image record (reference: recordio.py unpack_img)."""
    from . import image

    header, buf = unpack(s)
    img = image.imdecode(buf, flag=1 if iscolor != 0 else 0, to_ndarray=False)
    return header, img
