"""Runtime feature introspection + the bounded accelerator dial.

TPU-native equivalent of the reference's `python/mxnet/runtime.py` +
`src/libinfo.cc` (build-feature flags queryable at runtime: `Features()`,
`feature_list()`, `is_enabled` — reference runtime.py:28). Features here
describe the JAX/XLA backend actually present in the process instead of
compile-time `USE_*` flags.

`dial_devices` is the fast-fail front door to `jax.devices()`: a wedged
axon PJRT tunnel blocks the bare call forever (the ROADMAP item-5 failure
class — 900s burned per bench row), so the dial runs on a deadline thread
(the PR-2 bounded-rendezvous pattern), brackets itself with
flight-recorder events, and caches the device topology to a JSON file
(``MXTPU_TOPOLOGY_CACHE``) so a later failed dial can still say what
hardware went missing.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time

from . import env as _env
from . import telemetry
from .base import MXNetError, atomic_writer

__all__ = ["Feature", "Features", "feature_list",
           "PEAK_BF16_TFLOPS", "chip_peak_tflops",
           "dial_devices", "cached_topology"]

Feature = collections.namedtuple("Feature", ["name", "enabled"])


def _detect():
    feats = {}

    def add(name, fn):
        try:
            feats[name] = bool(fn())
        except Exception:
            feats[name] = False

    import jax

    platforms = {d.platform for d in jax.devices()}
    add("TPU", lambda: "tpu" in platforms)
    add("GPU", lambda: "gpu" in platforms or "cuda" in platforms)
    add("CPU", lambda: True)
    add("F16C", lambda: True)          # fp16 compute available through XLA
    add("BF16", lambda: True)          # native MXU dtype
    add("INT8", lambda: True)          # int8 dot via XLA (quantization path)
    add("PALLAS", _pallas_available)
    add("DIST_KVSTORE", lambda: True)  # collectives-backed kvstore
    add("OPENCV", _cv_available)       # image decode path
    add("NATIVE_IO", _native_io_available)  # C++ recordio/pipeline library
    add("SIGNAL_HANDLER", lambda: True)
    add("PROFILER", lambda: True)
    return feats


def _pallas_available():
    from jax.experimental import pallas  # noqa: F401

    return True


def _cv_available():
    try:
        import cv2  # noqa: F401

        return True
    except ImportError:
        from PIL import Image  # noqa: F401

        return True


def _native_io_available():
    from .lib import native

    return native.available()


class Features(collections.OrderedDict):
    """Mapping name -> Feature (reference: runtime.py:45 class Features)."""

    instance = None

    def __init__(self):
        super().__init__(
            (name, Feature(name, enabled)) for name, enabled in _detect().items())

    def __repr__(self):
        return "[%s]" % ", ".join(
            "%s%s" % ("✔ " if f.enabled else "✖ ", f.name) for f in self.values())

    def is_enabled(self, feature_name):
        """reference: runtime.py:78 Features.is_enabled."""
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError("feature '%s' is unknown" % feature_name)
        return self[feature_name].enabled


def feature_list():
    """List of Feature tuples (reference: runtime.py:95 feature_list)."""
    return list(Features().values())


# ---------------------------------------------------------------------------
# chip peak FLOPs table (shared by bench.py and tools/mfu_probe*.py)
# ---------------------------------------------------------------------------

# Peak dense-matmul TFLOPS per chip, bf16 (fp32 runs the MXU in multi-pass
# mode at roughly 1/8 of bf16 peak on v4+; callers report fp32 MFU against
# the bf16 peak so numbers stay conservative and comparable).
PEAK_BF16_TFLOPS = {
    "TPU v2": 46.0,
    "TPU v3": 123.0,
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,     # v5e
    "TPU v5e": 197.0,
    "TPU v5": 459.0,          # v5p
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,     # Trillium / v6e
    "TPU v6e": 918.0,
    "TPU7x": 4600.0,
}


def chip_peak_tflops(device):
    """Peak bf16 TFLOP/s for a jax device, or None if unknown."""
    kind = (getattr(device, "device_kind", "") or "").lower()
    # longest table key first so "TPU v5 lite" wins over "TPU v5"
    for name, peak in sorted(PEAK_BF16_TFLOPS.items(),
                             key=lambda kv: -len(kv[0])):
        if kind.startswith(name.lower()):
            return peak
    return None


# ---------------------------------------------------------------------------
# bounded accelerator dial (ROADMAP item 5)
# ---------------------------------------------------------------------------

def _topology_cache_path():
    return _env.raw("MXTPU_TOPOLOGY_CACHE") or None


def cached_topology(path=None):
    """The last successfully dialed device topology (platform, device
    kind, count, timestamp) from the `MXTPU_TOPOLOGY_CACHE` file, or None
    when no cache exists / the var is unset."""
    path = path or _topology_cache_path()
    if not path:
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _write_topology(devices, path=None):
    path = path or _topology_cache_path()
    if not path:
        return
    try:
        with atomic_writer(path, "w") as f:
            json.dump({
                "platform": devices[0].platform,
                "device_kind": getattr(devices[0], "device_kind", None),
                "device_count": len(devices),
                "time": time.time(),
            }, f, indent=1)
    except OSError:
        pass  # the cache is best-effort; never fail a successful dial


def dial_devices(timeout_s=None, cache=True):
    """`jax.devices()` behind a fail-fast deadline.

    The PJRT dial over a wedged axon tunnel blocks forever; XLA offers no
    client-side timeout. Same structure as the PR-2 bounded rendezvous:
    the dial runs on a daemon thread, we wait `timeout_s`
    (``MXTPU_DIAL_TIMEOUT_S``), and on expiry raise a diagnosable
    `MXNetError` — including the last cached topology, so the caller can
    label its artifact with the hardware that went missing — while the
    probe thread stays parked in the dial (it completes or dies with the
    process; a second `dial_devices` call re-waits on the same dial).

    Every dial is bracketed with flight-recorder events
    (``pjrt_dial_start`` / ``_ok`` / ``_timeout`` / ``_error``), and a
    successful non-CPU dial refreshes the ``MXTPU_TOPOLOGY_CACHE`` file.
    """
    if timeout_s is None:
        timeout_s = _env.get("MXTPU_DIAL_TIMEOUT_S")
    done = threading.Event()
    result, err = [], []

    def probe():
        try:
            import jax

            result.extend(jax.devices())
        except Exception as e:  # noqa: BLE001 — reported to the caller
            err.append(e)
        done.set()

    telemetry.record_event("pjrt_dial_start", timeout_s=timeout_s,
                           pid=os.getpid())
    t0 = time.monotonic()
    with _DIAL_LOCK:
        # reuse a still-parked (or successfully completed) dial thread; a
        # FAILED past dial is dropped so the retry actually redials
        if _DIAL_THREAD and _DIAL_THREAD[0][1].is_set() and _DIAL_THREAD[0][3]:
            _DIAL_THREAD.clear()
        if not _DIAL_THREAD:
            t = threading.Thread(target=probe, daemon=True,
                                 name="mxtpu-pjrt-dial")
            _DIAL_THREAD.append((t, done, result, err))
            t.start()
        else:
            _, done, result, err = _DIAL_THREAD[0]
    if not done.wait(timeout_s):
        cached = cached_topology()
        telemetry.record_event("pjrt_dial_timeout", timeout_s=timeout_s,
                               cached_topology=cached)
        raise MXNetError(
            "accelerator dial (jax.devices()) still blocked after %.0fs "
            "(MXTPU_DIAL_TIMEOUT_S; wedged PJRT tunnel?). Last known "
            "topology: %s" % (timeout_s, cached or "none cached"))
    if err:
        telemetry.record_event("pjrt_dial_error", error=str(err[0])[:500])
        raise MXNetError("jax backend init failed: %s" % err[0]) from err[0]
    telemetry.record_event(
        "pjrt_dial_ok", seconds=round(time.monotonic() - t0, 3),
        platform=result[0].platform, device_count=len(result))
    if cache and result and result[0].platform != "cpu":
        _write_topology(result)
    return list(result)


_DIAL_LOCK = threading.Lock()
_DIAL_THREAD = []  # at most one parked dial thread per process
