"""Runtime feature introspection.

TPU-native equivalent of the reference's `python/mxnet/runtime.py` +
`src/libinfo.cc` (build-feature flags queryable at runtime: `Features()`,
`feature_list()`, `is_enabled` — reference runtime.py:28). Features here
describe the JAX/XLA backend actually present in the process instead of
compile-time `USE_*` flags.
"""
from __future__ import annotations

import collections

__all__ = ["Feature", "Features", "feature_list",
           "PEAK_BF16_TFLOPS", "chip_peak_tflops"]

Feature = collections.namedtuple("Feature", ["name", "enabled"])


def _detect():
    feats = {}

    def add(name, fn):
        try:
            feats[name] = bool(fn())
        except Exception:
            feats[name] = False

    import jax

    platforms = {d.platform for d in jax.devices()}
    add("TPU", lambda: "tpu" in platforms)
    add("GPU", lambda: "gpu" in platforms or "cuda" in platforms)
    add("CPU", lambda: True)
    add("F16C", lambda: True)          # fp16 compute available through XLA
    add("BF16", lambda: True)          # native MXU dtype
    add("INT8", lambda: True)          # int8 dot via XLA (quantization path)
    add("PALLAS", _pallas_available)
    add("DIST_KVSTORE", lambda: True)  # collectives-backed kvstore
    add("OPENCV", _cv_available)       # image decode path
    add("NATIVE_IO", _native_io_available)  # C++ recordio/pipeline library
    add("SIGNAL_HANDLER", lambda: True)
    add("PROFILER", lambda: True)
    return feats


def _pallas_available():
    from jax.experimental import pallas  # noqa: F401

    return True


def _cv_available():
    try:
        import cv2  # noqa: F401

        return True
    except ImportError:
        from PIL import Image  # noqa: F401

        return True


def _native_io_available():
    from .lib import native

    return native.available()


class Features(collections.OrderedDict):
    """Mapping name -> Feature (reference: runtime.py:45 class Features)."""

    instance = None

    def __init__(self):
        super().__init__(
            (name, Feature(name, enabled)) for name, enabled in _detect().items())

    def __repr__(self):
        return "[%s]" % ", ".join(
            "%s%s" % ("✔ " if f.enabled else "✖ ", f.name) for f in self.values())

    def is_enabled(self, feature_name):
        """reference: runtime.py:78 Features.is_enabled."""
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError("feature '%s' is unknown" % feature_name)
        return self[feature_name].enabled


def feature_list():
    """List of Feature tuples (reference: runtime.py:95 feature_list)."""
    return list(Features().values())


# ---------------------------------------------------------------------------
# chip peak FLOPs table (shared by bench.py and tools/mfu_probe*.py)
# ---------------------------------------------------------------------------

# Peak dense-matmul TFLOPS per chip, bf16 (fp32 runs the MXU in multi-pass
# mode at roughly 1/8 of bf16 peak on v4+; callers report fp32 MFU against
# the bf16 peak so numbers stay conservative and comparable).
PEAK_BF16_TFLOPS = {
    "TPU v2": 46.0,
    "TPU v3": 123.0,
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,     # v5e
    "TPU v5e": 197.0,
    "TPU v5": 459.0,          # v5p
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,     # Trillium / v6e
    "TPU v6e": 918.0,
    "TPU7x": 4600.0,
}


def chip_peak_tflops(device):
    """Peak bf16 TFLOP/s for a jax device, or None if unknown."""
    kind = (getattr(device, "device_kind", "") or "").lower()
    # longest table key first so "TPU v5 lite" wins over "TPU v5"
    for name, peak in sorted(PEAK_BF16_TFLOPS.items(),
                             key=lambda kv: -len(kv[0])):
        if kind.startswith(name.lower()):
            return peak
    return None
