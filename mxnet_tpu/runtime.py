"""Runtime feature introspection.

TPU-native equivalent of the reference's `python/mxnet/runtime.py` +
`src/libinfo.cc` (build-feature flags queryable at runtime: `Features()`,
`feature_list()`, `is_enabled` — reference runtime.py:28). Features here
describe the JAX/XLA backend actually present in the process instead of
compile-time `USE_*` flags.
"""
from __future__ import annotations

import collections

__all__ = ["Feature", "Features", "feature_list"]

Feature = collections.namedtuple("Feature", ["name", "enabled"])


def _detect():
    feats = {}

    def add(name, fn):
        try:
            feats[name] = bool(fn())
        except Exception:
            feats[name] = False

    import jax

    platforms = {d.platform for d in jax.devices()}
    add("TPU", lambda: "tpu" in platforms)
    add("GPU", lambda: "gpu" in platforms or "cuda" in platforms)
    add("CPU", lambda: True)
    add("F16C", lambda: True)          # fp16 compute available through XLA
    add("BF16", lambda: True)          # native MXU dtype
    add("INT8", lambda: True)          # int8 dot via XLA (quantization path)
    add("PALLAS", _pallas_available)
    add("DIST_KVSTORE", lambda: True)  # collectives-backed kvstore
    add("OPENCV", _cv_available)       # image decode path
    add("NATIVE_IO", _native_io_available)  # C++ recordio/pipeline library
    add("SIGNAL_HANDLER", lambda: True)
    add("PROFILER", lambda: True)
    return feats


def _pallas_available():
    from jax.experimental import pallas  # noqa: F401

    return True


def _cv_available():
    try:
        import cv2  # noqa: F401

        return True
    except ImportError:
        from PIL import Image  # noqa: F401

        return True


def _native_io_available():
    from .lib import native

    return native.available()


class Features(collections.OrderedDict):
    """Mapping name -> Feature (reference: runtime.py:45 class Features)."""

    instance = None

    def __init__(self):
        super().__init__(
            (name, Feature(name, enabled)) for name, enabled in _detect().items())

    def __repr__(self):
        return "[%s]" % ", ".join(
            "%s%s" % ("✔ " if f.enabled else "✖ ", f.name) for f in self.values())

    def is_enabled(self, feature_name):
        """reference: runtime.py:78 Features.is_enabled."""
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError("feature '%s' is unknown" % feature_name)
        return self[feature_name].enabled


def feature_list():
    """List of Feature tuples (reference: runtime.py:95 feature_list)."""
    return list(Features().values())
