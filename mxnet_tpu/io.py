"""Data iterators.

Reference: python/mxnet/io/io.py (DataIter :178, NDArrayIter :489,
PrefetchingIter :345, ResizeIter) plus the C++ iterator registry
(src/io/iter_mnist.cc:80 MNISTIter, iter_csv.cc:164 CSVIter,
iter_image_recordio_2.cc:766 ImageRecordIter). TPU-native: iterators are
host-side Python/numpy producers (decode/augment on CPU), double-buffered via
a background thread (PrefetchingIter) — device transfer is async through
PJRT, so the pipeline overlaps with compute like the reference's
iter_prefetcher.h chain."""
from __future__ import annotations

import collections

import numpy as _np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MNISTIter", "CSVIter", "ImageRecordIter",
           "LibSVMIter"]


class DataDesc(collections.namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """reference: io.py DataDesc"""

    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    """reference: io.py DataBatch"""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Base iterator (reference: io.py:178)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """reference: io.py _init_data"""
    if data is None:
        data = []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = collections.OrderedDict([(default_name, data[0])])
        else:
            data = collections.OrderedDict(
                [("_%d_%s" % (i, default_name), d) for i, d in enumerate(data)])
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, list or dict")
    return collections.OrderedDict(
        (k, v if isinstance(v, NDArray) else nd.array(v)) for k, v in data.items())


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.py:489)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = _np.arange(len(next(iter(self.data.values()))))
        if shuffle:
            _np.random.shuffle(self.idx)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.num_data = len(self.idx)
        if last_batch_handle == "discard":
            self.num_data = (self.num_data // batch_size) * batch_size
        assert self.num_data >= batch_size, "batch_size larger than data size"
        self.cursor = -batch_size

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]), v.dtype)
                for k, v in self.data.items()]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + tuple(v.shape[1:]), v.dtype)
                for k, v in self.label.items()]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self.idx)
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _take(self, arrs):
        end = self.cursor + self.batch_size
        if end <= self.num_data:
            sel = self.idx[self.cursor:end]
        else:  # pad: wrap around (reference pads from the beginning)
            sel = _np.concatenate([self.idx[self.cursor:self.num_data],
                                   self.idx[:end - self.num_data]])
        out = []
        for v in arrs.values():
            out.append(v.take(nd.array(sel, dtype="int32")))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        end = self.cursor + self.batch_size
        if self.last_batch_handle == "pad" and end > self.num_data:
            return end - self.num_data
        return 0


class ResizeIter(DataIter):
    """Resize an iterator to `size` batches per epoch (reference: io.py ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Double-buffered prefetch (reference: io.py:345 + src/io/iter_prefetcher.h).
    One background thread per wrapped iterator keeps the next batch ready."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = getattr(iters[0], "batch_size", 0)
        self._start()   # arms THIS generation's PrefetchBuffer

    @property
    def provide_data(self):
        out = []
        for i, it in enumerate(self.iters):
            descs = it.provide_data
            if self.rename_data:
                descs = [DataDesc(self.rename_data[i].get(d.name, d.name),
                                  d.shape, d.dtype, d.layout) for d in descs]
            out.extend(descs)
        return out

    @property
    def provide_label(self):
        out = []
        for i, it in enumerate(self.iters):
            descs = it.provide_label
            if self.rename_label:
                descs = [DataDesc(self.rename_label[i].get(d.name, d.name),
                                  d.shape, d.dtype, d.layout) for d in descs]
            out.extend(descs)
        return out

    def _produce(self):
        # runs on the PrefetchBuffer producer thread (which captures its
        # queue/stop as locals — the stale-worker epoch-bleed fix lives in
        # data/core, shared by every prefetching surface)
        batches = [it.next() for it in self.iters]
        data = sum([b.data for b in batches], [])
        label = sum([(b.label or []) for b in batches], [])
        return DataBatch(data=data, label=label, pad=batches[0].pad,
                         index=batches[0].index)

    def _start(self):
        from .data.core import PrefetchBuffer

        self._buf = PrefetchBuffer(self._produce, depth=2,
                                   name="mxtpu-io-prefetch",
                                   owner="PrefetchingIter.reset", src="io")

    def reset(self):
        # stop + join the producer BEFORE rewinding: resetting the wrapped
        # iterators under a live reader corrupts the next epoch
        self._buf.close()
        for it in self.iters:
            it.reset()
        self._start()

    def next(self):
        return self._buf.get()

    def iter_next(self):
        raise MXNetError("use next() with PrefetchingIter")


class MNISTIter(NDArrayIter):
    """MNIST file iterator (reference: src/io/iter_mnist.cc:80). Reads the
    standard idx files; flat or (1,28,28) images."""

    def __init__(self, image="train-images-idx3-ubyte", label="train-labels-idx1-ubyte",
                 batch_size=128, shuffle=True, flat=False, silent=False,
                 seed=None, **kwargs):
        import gzip
        import os
        import struct

        def read(path):
            if os.path.exists(path):
                with open(path, "rb") as f:
                    return f.read()
            if os.path.exists(path + ".gz"):
                with gzip.open(path + ".gz", "rb") as f:
                    return f.read()
            raise MXNetError("MNIST file %s not found" % path)

        raw = read(label)
        lab = _np.frombuffer(raw[8:], dtype=_np.uint8).astype(_np.float32)
        raw = read(image)
        _, num, rows, cols = struct.unpack(">IIII", raw[:16])
        img = _np.frombuffer(raw[16:], dtype=_np.uint8).astype(_np.float32) / 255.0
        img = img.reshape(num, rows * cols) if flat else img.reshape(num, 1, rows, cols)
        super().__init__(img, lab, batch_size=batch_size, shuffle=shuffle,
                         data_name="data", label_name="label")


class CSVIter(DataIter):
    """CSV iterator (reference: src/io/iter_csv.cc:164)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = _np.loadtxt(data_csv, delimiter=",", dtype=_np.float32, ndmin=2)
        self._data = data.reshape((-1,) + tuple(data_shape))
        if label_csv is not None:
            label = _np.loadtxt(label_csv, delimiter=",", dtype=_np.float32, ndmin=2)
            self._label = label.reshape((-1,) + tuple(label_shape))
        else:
            self._label = _np.zeros((len(self._data), 1), _np.float32)
        self._inner = NDArrayIter(self._data, self._label, batch_size=batch_size,
                                  last_batch_handle="pad" if round_batch else "discard",
                                  data_name="data", label_name="label")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class LibSVMIter(DataIter):
    """LibSVM-format iterator (reference: src/io/iter_libsvm.cc:67). Loads to
    dense host arrays (row_sparse storage arrives with the sparse module)."""

    def __init__(self, data_libsvm, data_shape, label_libsvm=None, batch_size=1,
                 **kwargs):
        super().__init__(batch_size)
        num_col = int(_np.prod(data_shape))
        rows = []
        labels = []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = _np.zeros(num_col, _np.float32)
                for tok in parts[1:]:
                    k, v = tok.split(":")
                    row[int(k)] = float(v)
                rows.append(row)
        data = _np.stack(rows).reshape((-1,) + tuple(data_shape))
        self._inner = NDArrayIter(data, _np.asarray(labels, _np.float32),
                                  batch_size=batch_size, data_name="data",
                                  label_name="label")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


def ImageRecordIter(path_imgrec=None, data_shape=(3, 224, 224), batch_size=1,
                    shuffle=False, rand_crop=False, rand_mirror=False,
                    mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                    std_b=1.0, resize=-1, label_width=1, preprocess_threads=4,
                    prefetch_buffer=4, **kwargs):
    """Augmenting RecordIO image iterator (reference:
    src/io/iter_image_recordio_2.cc:766 + image_aug_default.cc). Returns the
    threaded python pipeline from mxnet_tpu.image."""
    from . import image

    return image.ImageRecordIterPy(
        path_imgrec=path_imgrec, data_shape=data_shape, batch_size=batch_size,
        shuffle=shuffle, rand_crop=rand_crop, rand_mirror=rand_mirror,
        mean=(mean_r, mean_g, mean_b), std=(std_r, std_g, std_b), resize=resize,
        label_width=label_width, preprocess_threads=preprocess_threads,
        prefetch_buffer=prefetch_buffer)
