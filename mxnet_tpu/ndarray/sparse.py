"""Sparse NDArray storage: row_sparse and csr.

TPU-native equivalent of the reference's sparse storage types
(include/mxnet/ndarray.h:61-66 kRowSparseStorage/kCSRStorage; Python front
python/mxnet/ndarray/sparse.py — RowSparseNDArray, CSRNDArray,
row_sparse_array :?, csr_matrix; kernels src/operator/tensor/cast_storage-inl.h,
dot-inl.h sparse paths, sparse_retain, square_sum).

TPU-first design: component arrays (data/indices/indptr) are ordinary
jax.Arrays; every sparse kernel lowers to XLA gather/scatter/segment-sum,
which the TPU executes natively — there is no CUDA-style hand-written
scatter kernel to port. Shapes of the components are static per array
instance, so eager ops compile once per (nnz, shape) signature. Autograd
stays dense (SURVEY §7.8c): gradients densify on the tape; sparsity is an
*optimizer/storage/io* optimization (lazy row updates, row_sparse push/pull),
matching where the reference actually exploits it.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray, array as _dense_array

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "cast_storage", "sparse_retain",
           "retain", "dot", "square_sum", "add", "zeros", "empty", "array"]


class BaseSparseNDArray(NDArray):
    """Common base (reference: sparse.py BaseSparseNDArray)."""

    __slots__ = ("_shape",)

    # sparse arrays keep a logical dense shape + component jax arrays in
    # _data (a dict) — NDArray methods that assume one buffer are overridden
    @property
    def shape(self):
        return self._shape

    @property
    def size(self):
        out = 1
        for s in self._shape:
            out *= s
        return int(out)

    @property
    def ndim(self):
        return len(self._shape)

    def wait_to_read(self):
        for v in self._data.values():
            v.block_until_ready()
        return self

    wait_to_write = wait_to_read

    @property
    def dtype(self):
        return _np.dtype(self._data["data"].dtype)

    def asnumpy(self):
        return _np.asarray(self.todense().asnumpy())

    def astype(self, dtype, copy=True):
        """Cast the stored values, keeping sparsity (reference: sparse.py
        BaseSparseNDArray.astype)."""
        out = type(self).__new__(type(self))
        NDArray.__init__(out, None, ctx=self._ctx)
        out._shape = self._shape
        comps = dict(self._data)
        comps["data"] = comps["data"].astype(dtype)
        out._data = comps
        return out

    def todense(self):
        return self.tostype("default")

    def __repr__(self):
        return "\n<%s %s @%s>" % (type(self).__name__,
                                  "x".join(str(s) for s in self._shape), self._ctx)

    def __getitem__(self, key):
        return self.todense()[key]

    def __setitem__(self, key, value):
        raise MXNetError("sparse NDArray does not support item assignment")

    def copyto(self, other):
        """Sparse-aware copy (reference: sparse.py BaseSparseNDArray.copyto):
        to a Context -> same-stype copy on that device; to a dense NDArray ->
        densify; to a same-stype sparse -> component copy."""
        import jax

        from ..context import Context

        if isinstance(other, Context):
            out = type(self).__new__(type(self))
            NDArray.__init__(out, None, ctx=other)
            out._shape = self._shape
            out._data = {k: jax.device_put(v, other.jax_device())
                         for k, v in self._data.items()}
            return out
        if isinstance(other, BaseSparseNDArray):
            if type(other) is not type(self):
                raise MXNetError("copyto: stype mismatch (%s -> %s)"
                                 % (self.stype, other.stype))
            other._shape = self._shape
            other._data = {k: jax.device_put(v, other._ctx.jax_device())
                           for k, v in self._data.items()}
            other._row_ids_cache = None  # derived cache follows components
            return other
        if isinstance(other, NDArray):
            # densify then reuse NDArray.copyto for the device transfer
            return self.tostype("default").copyto(other)
        raise TypeError("copyto: expected NDArray or Context")


class RowSparseNDArray(BaseSparseNDArray):
    """row_sparse: (indices, values) over the first dimension (reference:
    sparse.py RowSparseNDArray; storage ndarray.h:64 kRowSparseStorage).
    `indices` is sorted unique int64 of present rows; `data` is
    (nnz_rows,) + shape[1:]."""

    @property
    def stype(self):
        return "row_sparse"

    @property
    def data(self):
        return NDArray(self._data["data"], ctx=self._ctx)

    values = data

    @property
    def indices(self):
        return NDArray(self._data["indices"], ctx=self._ctx)

    @property
    def num_rows(self):
        return int(self._data["indices"].shape[0])

    def tostype(self, stype):
        import jax.numpy as jnp

        if stype == "row_sparse":
            return self
        if stype == "default":
            dense = jnp.zeros(self._shape, self._data["data"].dtype)
            dense = dense.at[self._data["indices"]].set(self._data["data"])
            return NDArray(dense, ctx=self._ctx)
        if stype == "csr":
            return self.todense().tostype("csr")
        raise MXNetError("unknown stype '%s'" % stype)

    def retain(self, row_ids):
        return sparse_retain(self, row_ids)


class CSRNDArray(BaseSparseNDArray):
    """csr: 2-D compressed sparse row (reference: sparse.py CSRNDArray;
    storage ndarray.h:65 kCSRStorage). Components: data (nnz,),
    indices (nnz,) column ids, indptr (rows+1,)."""

    @property
    def stype(self):
        return "csr"

    @property
    def data(self):
        return NDArray(self._data["data"], ctx=self._ctx)

    @property
    def indices(self):
        return NDArray(self._data["indices"], ctx=self._ctx)

    @property
    def indptr(self):
        return NDArray(self._data["indptr"], ctx=self._ctx)

    @property
    def nnz(self):
        return int(self._data["data"].shape[0])

    def _row_ids(self):
        """nnz-length row id per element (host-computed from indptr; static
        per instance — memoized, so the differentiable dot's forward and
        backward share one device->host sync)."""
        cached = getattr(self, "_row_ids_cache", None)
        if cached is not None:
            return cached
        indptr = _np.asarray(self._data["indptr"])
        counts = _np.diff(indptr)
        out = _np.repeat(_np.arange(self._shape[0], dtype=_np.int32), counts)
        self._row_ids_cache = out
        return out

    def tostype(self, stype):
        import jax.numpy as jnp

        if stype == "csr":
            return self
        if stype == "default":
            dense = jnp.zeros(self._shape, self._data["data"].dtype)
            rows = jnp.asarray(self._row_ids())
            dense = dense.at[rows, self._data["indices"]].set(self._data["data"])
            return NDArray(dense, ctx=self._ctx)
        if stype == "row_sparse":
            return self.todense().tostype("row_sparse")
        raise MXNetError("unknown stype '%s'" % stype)


# --------------------------------------------------------------------------
# construction (reference: sparse.py row_sparse_array / csr_matrix)
# --------------------------------------------------------------------------

def _make_rsp(data, indices, shape, ctx, dtype=None):
    import jax.numpy as jnp

    out = RowSparseNDArray.__new__(RowSparseNDArray)
    NDArray.__init__(out, None, ctx=ctx)
    out._shape = tuple(int(s) for s in shape)
    # indices are int32 on device: XLA's native index type (the reference
    # uses int64; jax truncates without x64 mode — values fit, divergence doc'd)
    out._data = {
        "data": jnp.asarray(data, dtype=dtype),
        "indices": jnp.asarray(indices).astype("int32"),
    }
    return out


def _make_csr(data, indptr, indices, shape, ctx, dtype=None):
    import jax.numpy as jnp

    out = CSRNDArray.__new__(CSRNDArray)
    NDArray.__init__(out, None, ctx=ctx)
    out._shape = tuple(int(s) for s in shape)
    out._data = {
        "data": jnp.asarray(data, dtype=dtype),
        "indices": jnp.asarray(_np.asarray(indices, dtype="int64"), dtype="int32"),
        "indptr": jnp.asarray(_np.asarray(indptr, dtype="int64"), dtype="int32"),
    }
    return out


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray (reference: sparse.py row_sparse_array).
    Accepts (data, indices) tuple, a dense source, or another sparse array."""
    ctx = ctx or current_context()
    if isinstance(arg1, RowSparseNDArray):
        return arg1
    if isinstance(arg1, tuple) and len(arg1) == 2 and not isinstance(arg1[0], int):
        data, indices = arg1
        data = _np.asarray(getattr(data, "asnumpy", lambda: data)()
                           if isinstance(data, NDArray) else data,
                           dtype=dtype or "float32")
        indices = _np.asarray(getattr(indices, "asnumpy", lambda: indices)()
                              if isinstance(indices, NDArray) else indices,
                              dtype="int64")
        order = _np.argsort(indices)
        if shape is None:
            top = int(indices.max()) + 1 if indices.size else 0
            shape = (top,) + data.shape[1:]
        return _make_rsp(data[order], indices[order], shape, ctx,
                         dtype=dtype or data.dtype)
    # dense-like source
    dense = arg1 if isinstance(arg1, NDArray) else _dense_array(arg1, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray (reference: sparse.py csr_matrix). Accepts
    (data, indices, indptr) — scipy argument order — or a dense source."""
    ctx = ctx or current_context()
    if isinstance(arg1, CSRNDArray):
        return arg1
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        to_np = lambda a, dt: _np.asarray(
            a.asnumpy() if isinstance(a, NDArray) else a, dtype=dt)
        data = to_np(data, dtype or "float32")
        indices = to_np(indices, "int64")
        indptr = to_np(indptr, "int64")
        if shape is None:
            shape = (len(indptr) - 1, int(indices.max()) + 1 if indices.size else 0)
        return _make_csr(data, indptr, indices, shape, ctx, dtype=dtype or data.dtype)
    dense = arg1 if isinstance(arg1, NDArray) else _dense_array(arg1, ctx=ctx, dtype=dtype)
    return cast_storage(dense, "csr")


def array(source_array, ctx=None, dtype=None):
    """Sparse-aware nd.sparse.array (reference: sparse.py array)."""
    if isinstance(source_array, BaseSparseNDArray):
        return source_array
    try:
        import scipy.sparse as sps

        if sps.issparse(source_array):
            csr = source_array.tocsr()
            return csr_matrix((csr.data, csr.indices, csr.indptr),
                              shape=csr.shape, ctx=ctx, dtype=dtype)
    except ImportError:
        pass
    return _dense_array(source_array, ctx=ctx, dtype=dtype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    """All-zero sparse array (reference: sparse.py zeros)."""
    ctx = ctx or current_context()
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    if stype == "row_sparse":
        return _make_rsp(_np.zeros((0,) + shape[1:], dtype=dtype),
                         _np.zeros((0,), dtype="int64"), shape, ctx, dtype=dtype)
    if stype == "csr":
        return _make_csr(_np.zeros((0,), dtype=dtype),
                         _np.zeros((shape[0] + 1,), dtype="int64"),
                         _np.zeros((0,), dtype="int64"), shape, ctx, dtype=dtype)
    if stype == "default":
        from . import ndarray as _nd_mod

        return _nd_mod.zeros(shape, ctx=ctx, dtype=dtype)
    raise MXNetError("unknown stype '%s'" % stype)


def empty(stype, shape, ctx=None, dtype="float32"):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


# --------------------------------------------------------------------------
# kernels (reference: src/operator/tensor/cast_storage-inl.h, sparse_retain,
# dot-inl.h, square_sum-inl.h — all as XLA gather/scatter/segment ops here)
# --------------------------------------------------------------------------

def cast_storage(arr, stype):
    """Convert between storage types (reference: cast_storage op,
    src/operator/tensor/cast_storage.cc)."""
    if isinstance(arr, BaseSparseNDArray):
        return arr.tostype(stype)
    if stype == "default":
        return arr
    if stype == "row_sparse":
        import jax.numpy as jnp

        if arr.ndim < 1:
            raise MXNetError("row_sparse needs ndim >= 1")
        # device-side row scan: only the (small) index vector syncs to host;
        # the dense payload never round-trips (unlike a numpy formulation —
        # this runs every trainer.step for sparse-grad params)
        data_j = arr._data
        mask = jnp.any(data_j.reshape(data_j.shape[0], -1) != 0, axis=1)
        nz_rows = jnp.nonzero(mask)[0]
        return _make_rsp(data_j[nz_rows], nz_rows, arr.shape,
                         arr.context, dtype=data_j.dtype)
    if stype == "csr":
        np_arr = arr.asnumpy()
        if np_arr.ndim != 2:
            raise MXNetError("csr storage requires a 2-D array")
        rows, cols = _np.nonzero(np_arr)
        indptr = _np.zeros(np_arr.shape[0] + 1, dtype="int64")
        _np.add.at(indptr, rows + 1, 1)
        indptr = _np.cumsum(indptr)
        return _make_csr(np_arr[rows, cols], indptr, cols.astype("int64"),
                         np_arr.shape, arr.context, dtype=np_arr.dtype)
    raise MXNetError("unknown stype '%s'" % stype)


def sparse_retain(arr, indices):
    """Keep only the requested rows (reference: sparse_retain op,
    src/operator/tensor/sparse_retain.cc)."""
    import jax.numpy as jnp

    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("sparse_retain expects a RowSparseNDArray")
    want = indices._data if isinstance(indices, NDArray) else jnp.asarray(indices)
    have = arr._data["indices"]
    keep = jnp.nonzero(jnp.isin(have, want.astype(have.dtype)))[0]
    data = arr._data["data"][keep]
    return _make_rsp(data, have[keep], arr.shape, arr.context, dtype=data.dtype)


retain = sparse_retain


def _csr_dot_math(lhs, dense, transpose_a):
    """The SpMM kernel: csr x dense (or csr.T x dense) via XLA
    segment_sum / scatter-add. `dense` is an NDArray; returns NDArray."""
    import jax
    import jax.numpy as jnp

    vec = dense.ndim == 1
    rows = jnp.asarray(lhs._row_ids())
    cols = lhs._data["indices"]
    vals = lhs._data["data"]
    if not transpose_a:
        # out[m(, n)] = sum_k csr[m, k] * dense[k(, n)]
        gathered = dense._data[cols]          # (nnz,) or (nnz, n)
        prods = vals * gathered if vec else vals[:, None] * gathered
        out = jax.ops.segment_sum(prods, rows, num_segments=lhs.shape[0])
        return NDArray(out, ctx=dense.context)
    # out[k(, n)] = sum_m csr[m, k] * dense[m(, n)]
    g_rows = dense._data[rows]
    prods = vals * g_rows if vec else vals[:, None] * g_rows
    out_shape = (lhs.shape[1],) if vec else (lhs.shape[1], dense.shape[1])
    out = jnp.zeros(out_shape, prods.dtype)
    out = out.at[cols].add(prods)
    return NDArray(out, ctx=dense.context)


def _get_csr_dot_cls():
    """Module-level Function subclass, created once (lazy: autograd imports
    ndarray, so this module cannot import autograd at top level)."""
    global _CSRDotFn
    if _CSRDotFn is None:
        from ..autograd import Function

        class _CSRDot(Function):
            def forward(self, rhs_nd):
                d = rhs_nd.tostype("default") \
                    if isinstance(rhs_nd, BaseSparseNDArray) else rhs_nd
                return _csr_dot_math(self._lhs, d, self._transpose_a)

            def backward(self, ograd):
                return _csr_dot_math(self._lhs, ograd,
                                     not self._transpose_a)

        _CSRDotFn = _CSRDot
    return _CSRDotFn


_CSRDotFn = None


def _csr_dot_fn(lhs, transpose_a):
    """Tape node for dot(csr, w): forward densifies the rhs internally so
    the recorded input is the weight itself (even a RowSparseNDArray);
    backward is the transposed SpMM — the csr matrix is data, not a
    differentiable input (reference: dot backward, dot-inl.h). Built on
    autograd.Function so grads flow on the eager tape, and write-back
    casts to the weight's attach_grad stype (row_sparse lazy updates)."""
    fn = _get_csr_dot_cls()()
    fn._lhs = lhs
    fn._transpose_a = transpose_a
    return fn


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (reference: src/operator/tensor/dot-inl.h —
    csr*dense and csr.T*dense paths; row_sparse via densify). Lowers to
    XLA segment_sum / scatter-add, the TPU-native SpMM formulation.
    Differentiable wrt the dense/row_sparse rhs (the reference's sparse
    linear-model training path, example/sparse/linear_classification)."""
    from .. import autograd as _ag

    if isinstance(lhs, CSRNDArray):
        if transpose_b:
            raise MXNetError("dot(csr, dense, transpose_b=True) unsupported "
                             "(matches reference)")
        dense_ndim = rhs.ndim
        if dense_ndim not in (1, 2):
            raise MXNetError("dot(csr, dense): rhs must be 1-D or 2-D, got %dD"
                             % dense_ndim)
        if _ag.is_recording():
            return _csr_dot_fn(lhs, transpose_a)(rhs)
        dense = rhs.tostype("default") \
            if isinstance(rhs, BaseSparseNDArray) else rhs
        return _csr_dot_math(lhs, dense, transpose_a)
    if isinstance(lhs, RowSparseNDArray) or isinstance(rhs, BaseSparseNDArray):
        l = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) else lhs
        r = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) else rhs
        return l.dot(r, transpose_a=transpose_a, transpose_b=transpose_b)
    return lhs.dot(rhs, transpose_a=transpose_a, transpose_b=transpose_b)


def square_sum(arr, axis=None, keepdims=False):
    """sum(x^2) touching only stored values (reference: _square_sum op,
    src/operator/tensor/square_sum-inl.h)."""
    import jax.numpy as jnp

    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("square_sum expects a RowSparseNDArray")
    vals = arr._data["data"]
    if axis is None:
        return NDArray(jnp.sum(vals * vals), ctx=arr.context)
    if axis in (1, -1) and arr.ndim == 2:
        # per-row sums live only at stored rows -> row_sparse result
        # (reference _square_sum emits row_sparse for axis=1)
        rows_sq = jnp.sum(vals * vals, axis=1, keepdims=keepdims)
        out_shape = (arr.shape[0],) + ((1,) if keepdims else ())
        return _make_rsp(rows_sq, arr._data["indices"], out_shape,
                         arr.context, dtype=rows_sq.dtype)
    return NDArray(jnp.sum(jnp.square(arr.todense()._data), axis=axis,
                           keepdims=keepdims), ctx=arr.context)


def add(lhs, rhs):
    """rsp + rsp -> rsp (union of rows; reference: elemwise_add sparse path).
    Stays on device — unique + segment_sum, no host round trip (this is the
    kvstore gradient-aggregation hot path)."""
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        import jax
        import jax.numpy as jnp

        if lhs.shape != rhs.shape:
            raise MXNetError("shape mismatch in sparse add")
        all_idx = jnp.concatenate([lhs._data["indices"], rhs._data["indices"]])
        all_data = jnp.concatenate([lhs._data["data"].astype(lhs.dtype),
                                    rhs._data["data"].astype(lhs.dtype)])
        union, inv = jnp.unique(all_idx, return_inverse=True)
        summed = jax.ops.segment_sum(all_data, inv.reshape(-1),
                                     num_segments=int(union.shape[0]))
        return _make_rsp(summed, union, lhs.shape, lhs.context,
                         dtype=summed.dtype)
    l = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) else rhs
    return l + r


# --------------------------------------------------------------------------
# lazy (row-wise) optimizer updates — the reason row_sparse exists
# (reference: src/operator/optimizer_op.cc sparse sgd/adam/adagrad kernels:
# only rows present in the gradient are touched)
# --------------------------------------------------------------------------

def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Lazy SGD: touch only grad.indices rows (reference:
    SGDUpdateRspImpl optimizer_op.cc)."""
    import jax.numpy as jnp

    rows = grad._data["indices"]
    g = grad._data["data"] * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    w_rows = weight._data[rows]
    g = g + wd * w_rows
    weight._set_data(weight._data.at[rows].add(-lr * g))
    return weight


def sgd_mom_update(weight, grad, mom, lr, momentum, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    import jax.numpy as jnp

    rows = grad._data["indices"]
    g = grad._data["data"] * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight._data[rows]
    new_mom_rows = momentum * mom._data[rows] - lr * g
    mom._set_data(mom._data.at[rows].set(new_mom_rows))
    weight._set_data(weight._data.at[rows].add(new_mom_rows))
    return weight


def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """Lazy Adam (reference: AdamUpdateRspImpl optimizer_op.cc; matches the
    reference's lazy_update semantics — moments of untouched rows stale)."""
    import jax.numpy as jnp

    rows = grad._data["indices"]
    g = grad._data["data"] * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight._data[rows]
    m_rows = beta1 * mean._data[rows] + (1 - beta1) * g
    v_rows = beta2 * var._data[rows] + (1 - beta2) * g * g
    mean._set_data(mean._data.at[rows].set(m_rows))
    var._set_data(var._data.at[rows].set(v_rows))
    weight._set_data(weight._data.at[rows].add(
        -lr * m_rows / (jnp.sqrt(v_rows) + epsilon)))
    return weight


def adagrad_update(weight, grad, history, lr, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    import jax.numpy as jnp

    rows = grad._data["indices"]
    g = grad._data["data"] * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    g = g + wd * weight._data[rows]
    h_rows = history._data[rows] + g * g
    history._set_data(history._data.at[rows].set(h_rows))
    weight._set_data(weight._data.at[rows].add(
        -lr * g / (jnp.sqrt(h_rows) + epsilon)))
    return weight
