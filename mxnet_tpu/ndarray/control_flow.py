"""Control-flow operators: foreach, while_loop, cond.

TPU-native equivalent of the reference's control-flow ops
(src/operator/control_flow.cc:476,487 — subgraphs executed via CachedOp :530;
Python front python/mxnet/ndarray/contrib.py foreach/while_loop/cond).

Two execution regimes, mirroring the reference's imperative-vs-symbolic split:

- **Eager** (concrete NDArrays): Python unroll, exactly like the reference's
  imperative foreach — every op lands on the autograd tape, so backward works
  with no extra machinery.
- **Traced** (inside hybridize/CachedOp/jit, detected by tracer-backed
  inputs): lowers to `lax.scan` / `lax.while_loop`-style masked scan /
  `lax.cond` so the XLA program stays O(1) in sequence length and fuses —
  the reason the reference needed subgraph ops at all. AD flows through the
  enclosing jax.vjp.
"""
from __future__ import annotations

from ..base import MXNetError
from .ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond"]


def _is_traced(*arrays):
    import jax

    return any(isinstance(a._data, jax.core.Tracer)
               for a in arrays if isinstance(a, NDArray))


def _as_list(x):
    if x is None:
        return [], True
    if isinstance(x, (list, tuple)):
        return list(x), False
    return [x], True


def _restore(lst, single):
    return lst[0] if single else list(lst)


def foreach(body, data, init_states):
    """Iterate `body` over data's first axis carrying states (reference:
    contrib.foreach python/mxnet/ndarray/contrib.py; op control_flow.cc:476).

    body(data_slice, states) -> (outputs, new_states)
    Returns (stacked_outputs, final_states).
    """
    data_list, data_single = _as_list(data)
    states, states_single = _as_list(init_states)
    if not data_list:
        raise MXNetError("foreach: data must be a non-empty NDArray or list")
    length = data_list[0].shape[0]
    if length == 0:
        raise MXNetError("foreach: data has zero-length axis 0 — outputs "
                         "would be undefined (reference raises too)")
    for d in data_list:
        if d.shape[0] != length:
            raise MXNetError("foreach: all data inputs need equal axis-0 length")

    if _is_traced(*(data_list + states)):
        return _foreach_scan(body, data_list, data_single, states, states_single)

    outputs = None
    for i in range(length):
        slices = _restore([d[i] for d in data_list], data_single)
        outs, new_states = body(slices, _restore(states, states_single))
        states, _ = _as_list(new_states)
        outs_l, outs_single = _as_list(outs)
        if outputs is None:
            outputs = [[] for _ in outs_l]
            single_out = outs_single
        for buf, o in zip(outputs, outs_l):
            buf.append(o)
    from . import stack as _stack

    stacked = [_stack(*buf, axis=0) for buf in outputs]
    return _restore(stacked, single_out), _restore(states, states_single)


def _foreach_scan(body, data_list, data_single, states, states_single):
    import jax

    from .. import autograd

    def scan_body(carry, xs):
        sts = _restore([NDArray(c) for c in carry], states_single)
        xnd = _restore([NDArray(x) for x in xs], data_single)
        with autograd.pause():
            outs, new_states = body(xnd, sts)
        new_l, _ = _as_list(new_states)
        outs_l, outs_single = _as_list(outs)
        scan_body.single_out = outs_single
        return tuple(s._data for s in new_l), tuple(o._data for o in outs_l)

    carry, ys = jax.lax.scan(scan_body,
                             tuple(s._data for s in states),
                             tuple(d._data for d in data_list))
    outs = [NDArray(y) for y in ys]
    final = [NDArray(c) for c in carry]
    return (_restore(outs, scan_body.single_out),
            _restore(final, states_single))


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Loop while cond holds, at most max_iterations (reference:
    contrib.while_loop python/mxnet/ndarray/contrib.py; op control_flow.cc:487).

    cond(*loop_vars) -> scalar; func(*loop_vars) -> (outputs, new_loop_vars).
    Returns (stacked_outputs padded to max_iterations, final_loop_vars) —
    same padding contract as the reference.
    """
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations (as in reference)")
    loop_vars, vars_single = _as_list(loop_vars)
    if not loop_vars:
        raise MXNetError("while_loop: loop_vars must be non-empty")

    if _is_traced(*loop_vars):
        return _while_loop_scan(cond, func, loop_vars, vars_single,
                                max_iterations)

    import jax.numpy as jnp

    outputs = None
    single_out = True
    steps = 0
    while steps < max_iterations and \
            bool(cond(*loop_vars).asnumpy().reshape(()).item()):
        outs, new_vars = func(*loop_vars)
        loop_vars, _ = _as_list(new_vars)
        outs_l, single_out = _as_list(outs)
        if outputs is None:
            outputs = [[] for _ in outs_l]
        for buf, o in zip(outputs, outs_l):
            buf.append(o)
        steps += 1
    if outputs is None:
        raise MXNetError("while_loop: cond was false on entry — outputs "
                         "undefined (reference raises too)")
    from . import stack as _stack

    stacked = []
    for buf in outputs:
        s = _stack(*buf, axis=0)
        if steps < max_iterations:
            # pad to max_iterations (reference pads; contents beyond the
            # actual step count are zeros)
            pad = jnp.zeros((max_iterations - steps,) + s.shape[1:], s.dtype)
            s = NDArray(jnp.concatenate([s._data, pad], axis=0), ctx=s.context)
        stacked.append(s)
    return _restore(stacked, single_out), _restore(loop_vars, vars_single)


def _while_loop_scan(cond, func, loop_vars, vars_single, max_iterations):
    """Traced lowering: scan over max_iterations with a done-mask — the
    static-shape formulation of while+stacked outputs XLA wants (the
    reference's symbolic while_loop keeps dynamic iteration but pads
    outputs identically)."""
    import jax
    import jax.numpy as jnp

    from .. import autograd

    def scan_body(carry, _):
        done, vars_j = carry
        vars_nd = [NDArray(v) for v in vars_j]
        with autograd.pause():
            pred = cond(*vars_nd)._data.reshape(()).astype(bool)
            outs, new_vars = func(*vars_nd)
        active = jnp.logical_and(jnp.logical_not(done), pred)
        new_l, _ = _as_list(new_vars)
        outs_l, outs_single = _as_list(outs)
        scan_body.single_out = outs_single
        kept = tuple(jnp.where(active, n._data, v)
                     for n, v in zip(new_l, vars_j))
        ys = tuple(jnp.where(active, o._data, jnp.zeros_like(o._data))
                   for o in outs_l)
        return (jnp.logical_or(done, jnp.logical_not(pred)), kept), ys

    init = (jnp.asarray(False), tuple(v._data for v in loop_vars))
    (done, vars_j), ys = jax.lax.scan(scan_body, init, None,
                                      length=max_iterations)
    outs = [NDArray(y) for y in ys]
    final = [NDArray(v) for v in vars_j]
    return (_restore(outs, scan_body.single_out), _restore(final, vars_single))


def cond(pred, then_func, else_func):
    """Conditional execution (reference: contrib.cond
    python/mxnet/ndarray/contrib.py; op control_flow.cc).

    pred: scalar NDArray; then_func/else_func: no-arg callables returning
    outputs (closure style, as in reference). Returns branch outputs.
    """
    if not _is_traced(pred):
        taken = bool(pred.asnumpy().reshape(()).item())
        return then_func() if taken else else_func()

    import jax

    from .. import autograd

    def wrap(fn):
        def run(_):
            with autograd.pause():
                outs = fn()
            outs_l, single = _as_list(outs)
            wrap.single = single
            return tuple(o._data for o in outs_l)

        return run

    t, e = wrap(then_func), wrap(else_func)
    ys = jax.lax.cond(pred._data.reshape(()).astype(bool), t, e, None)
    outs = [NDArray(y) for y in ys]
    return _restore(outs, wrap.single)
