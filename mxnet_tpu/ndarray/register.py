"""Generate module-level op functions from the registry.

Reference mechanism: python/mxnet/ndarray/register.py:170
`_init_op_module('mxnet','ndarray',_make_ndarray_function)` builds one Python
function per C++-registered op at import. We do the same against the jax op
registry: each OpDef gets a wrapper that splits NDArray arguments from attrs
by the op function's signature, then calls ndarray.invoke. Ops named
`_contrib_*` / `_linalg_*` / `_random_*` land in `nd.contrib` / `nd.linalg` /
`nd.random` namespaces like the reference."""
from __future__ import annotations

import inspect

from .. import ops as _ops
from .ndarray import NDArray, invoke


def _make_function(opdef):
    fn = opdef.fn
    try:
        sig = inspect.signature(fn)
        params = list(sig.parameters.values())
    except (TypeError, ValueError):
        params = []
    if opdef.needs_rng and params and params[0].name == "rng":
        params = params[1:]
    var_pos = any(p.kind == inspect.Parameter.VAR_POSITIONAL for p in params)
    pos_params = [p for p in params
                  if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                                inspect.Parameter.POSITIONAL_OR_KEYWORD)]
    pos_names = [p.name for p in pos_params]
    # arrays-first convention: a param is an array slot iff it has no
    # default or its default is None (optional array); a non-None default
    # marks an attr. Used to avoid injecting placeholder Nones for
    # unsupplied attrs that happen to precede the last supplied array.
    arrayish = {p.name: (p.default is inspect.Parameter.empty
                         or p.default is None) for p in pos_params}

    def generated(*args, out=None, name=None, **kwargs):
        inputs = []
        attrs = {}
        ctx = kwargs.pop("ctx", None)
        if var_pos:
            for a in args:
                if isinstance(a, NDArray):
                    inputs.append(a)
                else:
                    raise TypeError("%s: positional args must be NDArray" % opdef.name)
            kwargs.pop("num_args", None)
            attrs.update(kwargs)
        else:
            # bind arguments to their declared parameter slot: ops follow
            # the arrays-first convention (every param before the last
            # array param is an array param), so a None in an optional
            # array slot must ride as a positional placeholder — silently
            # shifting later arrays one slot left binds them to the WRONG
            # parameter (e.g. CTCLoss label_lengths landing in
            # pred_lengths when pred_lengths=None)
            slot = {}
            extras = []  # NDArray positionals past the declared signature
            consumed = set()
            for i, a in enumerate(args):
                pname = pos_names[i] if i < len(pos_names) else None
                if pname is None:
                    if isinstance(a, NDArray):
                        extras.append(a)
                    elif a is not None:
                        raise TypeError(
                            "%s: unexpected extra positional %r"
                            % (opdef.name, a))
                elif isinstance(a, NDArray) or a is None:
                    slot[pname] = a
                    consumed.add(pname)
                else:
                    attrs[pname] = a
                    consumed.add(pname)
            # NDArray kwargs bind to their own declared slot too
            for pname in pos_names:
                if pname not in consumed and pname in kwargs \
                        and isinstance(kwargs[pname], NDArray):
                    slot[pname] = kwargs.pop(pname)
            attrs.update({k: v for k, v in kwargs.items()
                          if not isinstance(v, NDArray)})
            order = {p: i for i, p in enumerate(pos_names)}
            arr_idx = [order[p] for p, v in slot.items()
                       if v is not None and p in order]
            if arr_idx:
                last = max(arr_idx)
                # interior gaps (optional arrays not provided) ride as
                # None so later arrays keep their declared position;
                # trailing Nones are dropped (defaults apply). Unsupplied
                # attr params (non-None default) are skipped, not turned
                # into placeholder Nones.
                inputs = [slot.get(p) for p in pos_names[:last + 1]
                          if p in slot or (p not in attrs and arrayish[p])]
            inputs.extend(extras)
        result = invoke(opdef.name, tuple(inputs), attrs, out=out)
        if ctx is not None and out is None and isinstance(result, NDArray):
            result = result.as_in_context(ctx)
        return result

    generated.__name__ = opdef.name
    # `params` already has the internal rng arg stripped (invoke injects the
    # key); show the signature callers actually use, plus the wrapper extras
    sig_str = "(%s)" % ", ".join(
        [str(p) for p in params] + ["out=None", "name=None"]) \
        if params else "(...)"
    generated.__doc__ = "%s%s\n\n%s\n(auto-generated from op '%s')" % (
        opdef.name, sig_str, (fn.__doc__ or "").strip(), opdef.name)
    return generated


class _OpNamespace(object):
    pass


def populate(target_module_dict):
    """Install generated functions into the nd module namespace."""
    contrib = _OpNamespace()
    linalg = _OpNamespace()
    random_ns = _OpNamespace()
    sparse_ns = _OpNamespace()
    image_ns = _OpNamespace()
    op_ns = _OpNamespace()
    seen = set()
    for name in _ops.list_ops():
        opdef = _ops.get(name)
        if id(opdef) in seen and name.startswith("_"):
            pass
        seen.add(id(opdef))
        f = _make_function(opdef)
        if name.startswith("_contrib_"):
            setattr(contrib, name[len("_contrib_"):], f)
        elif name.startswith("_linalg_"):
            setattr(linalg, name[len("_linalg_"):], f)
        elif name.startswith("_random_"):
            setattr(random_ns, name[len("_random_"):], f)
        elif name.startswith("_sample_"):
            setattr(random_ns, name[1:], f)
        elif name.startswith("_image_"):
            setattr(image_ns, name[len("_image_"):], f)
        if name.isidentifier():
            setattr(op_ns, name, f)  # flat mx.nd.op.* (reference op.py)
        if not name.startswith("_contrib_") and not name.startswith("_linalg_"):
            target_module_dict.setdefault(name, f)
    target_module_dict["contrib"] = contrib
    target_module_dict["linalg"] = linalg
    target_module_dict["random"] = random_ns
    target_module_dict["sparse"] = sparse_ns
    # op namespace mx.nd.image.* (reference image.cc family); the host-side
    # mx.image module (iterators/augmenters) is separate
    target_module_dict.setdefault("image", image_ns)
    target_module_dict.setdefault("op", op_ns)
    return contrib, linalg, random_ns, sparse_ns
