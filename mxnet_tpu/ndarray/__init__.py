"""mxnet_tpu.ndarray — imperative array API (reference: python/mxnet/ndarray)."""
from __future__ import annotations

from .ndarray import (NDArray, invoke, array, zeros, ones, full, empty, arange,
                      concat, save, load, waitall, from_jax)
from . import register as _register

_register.populate(globals())

# convenience re-exports matching mxnet.nd surface
from .ndarray import stack  # noqa: F401


def zeros_like(data):
    return invoke("zeros_like", (data,), {})


def ones_like(data):
    return invoke("ones_like", (data,), {})


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    out = invoke("_eye", (), {"N": N, "M": M, "k": k, "dtype": dtype})
    return out.as_in_context(ctx) if ctx is not None else out


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    out = invoke("_linspace", (), {"start": start, "stop": stop, "num": num,
                                   "endpoint": endpoint, "dtype": dtype})
    return out.as_in_context(ctx) if ctx is not None else out
