"""mxnet_tpu.ndarray — imperative array API (reference: python/mxnet/ndarray)."""
from __future__ import annotations

from .ndarray import (NDArray, invoke, array, zeros, ones, full, empty, arange,
                      concat, save, load, waitall, from_jax, from_dlpack,
                      to_dlpack_for_read, to_dlpack_for_write)
from . import register as _register

_register.populate(globals())

# convenience re-exports matching mxnet.nd surface
from .ndarray import stack  # noqa: F401
from . import sparse  # noqa: F401
from .sparse import BaseSparseNDArray, RowSparseNDArray, CSRNDArray  # noqa: F401

# control-flow ops live on nd.contrib (reference: ndarray/contrib.py)
from . import control_flow as _control_flow

contrib.foreach = _control_flow.foreach  # noqa: F821
contrib.while_loop = _control_flow.while_loop  # noqa: F821
contrib.cond = _control_flow.cond  # noqa: F821


def concatenate(arrays, axis=0, always_copy=True):
    """reference: ndarray.py concatenate (list -> one array along axis)."""
    # a bare NDArray is iterable row-wise, so list() would silently flatten
    # it; the reference asserts list-of-NDArray (ndarray.py:3724)
    if isinstance(arrays, NDArray):
        raise TypeError("concatenate expects a list of NDArrays, got NDArray")
    arrays = list(arrays)
    if not arrays:
        raise ValueError("concatenate expects a non-empty list")
    if len(arrays) == 1 and not always_copy:
        return arrays[0]
    return concat(*arrays, dim=axis)


def zeros_like(data):
    return invoke("zeros_like", (data,), {})


def ones_like(data):
    return invoke("ones_like", (data,), {})


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    out = invoke("_eye", (), {"N": N, "M": M, "k": k, "dtype": dtype})
    return out.as_in_context(ctx) if ctx is not None else out


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    out = invoke("_linspace", (), {"start": start, "stop": stop, "num": num,
                                   "endpoint": endpoint, "dtype": dtype})
    return out.as_in_context(ctx) if ctx is not None else out
