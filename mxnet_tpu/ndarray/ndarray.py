"""NDArray: the imperative tensor.

TPU-native equivalent of the reference's NDArray (include/mxnet/ndarray.h:82,
src/ndarray/ndarray.cc — SURVEY §2.1 N3) and of the Python front
(python/mxnet/ndarray/ndarray.py). Design mapping:

- Storage/Chunk + engine var  →  an immutable `jax.Array` (PJRT buffer). XLA
  owns allocation/pooling; async dispatch and dependency ordering come free
  from PJRT's stream semantics (the reference needed the threaded engine N1
  for this).
- in-place mutation (`+=`, `x[:]=`, optimizer updates, BN aux states)  →
  functional buffer *swap*: ops return new arrays and `_set_data` rebinds the
  handle, bumping a version counter (used by the autograd tape the way the
  reference uses engine var versioning).
- `WaitToRead/WaitToWrite` (ndarray.h:359)  →  `wait_to_read` =
  `block_until_ready`; async device errors surface here, matching the
  reference's deferred-exception rethrow (threaded_engine.cc:418).

Every operator call goes through `invoke()` — the equivalent of
`Imperative::Invoke` (src/imperative/imperative.cc:89): resolve OpDef, inject
train-mode / RNG key, run the per-(op, attrs) compiled executable, wrap
outputs, write back aux outputs, and record the call on the autograd tape.
"""
from __future__ import annotations

import inspect
import itertools

import numpy as _np

from .. import ops as _ops
from ..base import MXNetError, np_dtype, numeric_types
from ..context import Context, current_context
from ..telemetry import memory as _tm_memory

_uid_counter = itertools.count(1)

_INT32_MAX = 2**31 - 1


_WIDE_DTYPES = ("int64", "uint64", "float64")


def _x64_arming(arrays=(), shapes=(), dtypes=()):
    """Single authority for the large-tensor x64 policy (reference: int64
    TShape arithmetic exercised by tests/nightly/test_large_array.py).

    Arms when any shape has a dimension OR total element count past
    int32-max (JAX's default-int32 index arithmetic truncates silently —
    flat positions, size_array), or when any array/dtype is 64-bit-typed
    (value-magnitude cases the shape heuristic can't see, e.g. float64
    argmax indices). Inside the scope, gather/scatter positions and
    index-valued outputs become int64, exactly where int64 is semantically
    required; everywhere else the documented x64-off policy (README
    "int64") stands. Returns (context_manager, armed) so the armed state
    can join jit cache keys. Every x64 gate in the codebase must delegate
    here — a diverged copy reintroduces silent 32-bit truncation."""
    import contextlib
    import math

    shapes = list(shapes)
    dts = [str(d) for d in dtypes]
    for a in arrays:
        if isinstance(a, dict):  # sparse component dict
            a = a.get("data", a)
        if hasattr(a, "shape"):
            shapes.append(a.shape)
        if hasattr(a, "dtype"):
            dts.append(str(a.dtype))
    armed = any(d in _WIDE_DTYPES for d in dts) or any(
        any(dim > _INT32_MAX for dim in s) or math.prod(s) > _INT32_MAX
        for s in shapes)
    if armed:
        import jax

        # jax removed the top-level alias; the context manager lives in
        # jax.experimental on current releases. Probe both so the policy
        # survives either spelling.
        x64 = getattr(jax, "enable_x64", None)
        if x64 is None:
            from jax.experimental import enable_x64 as x64
        return x64(True), True
    return contextlib.nullcontext(), False


def _x64_if_large(*shapes):
    """Shape-triggered arm of the policy (see _x64_arming)."""
    return _x64_arming(shapes=shapes)[0]


def _x64_if_wide(*arrays):
    """Dtype-triggered arm of the policy (see _x64_arming)."""
    return _x64_arming(arrays=arrays)[0]


__all__ = ["NDArray", "invoke", "array", "zeros", "ones", "full", "empty",
           "arange", "concat", "save", "load", "waitall", "from_jax"]


class NDArray:
    """Multi-dimensional array on a device (reference: ndarray.h:82)."""

    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_grad_stype",
                 "_version", "_fresh_grad", "_uid", "_live_bytes")

    def __new__(cls, *args, **kwargs):
        # process-unique id for autograd tape keys: unlike id(), a uid is
        # never recycled after the array dies, so keys held past an array's
        # lifetime (autograd's freed-graph set) can't collide with new arrays
        self = super().__new__(cls)
        self._uid = next(_uid_counter)
        return self

    def __init__(self, data, ctx=None):
        self._data = data  # jax.Array
        self._ctx = ctx if ctx is not None else current_context()
        self._grad = None
        self._grad_req = "null"
        self._version = 0
        self._fresh_grad = False
        # live-memory accounting (telemetry.memory): handles created minus
        # handles collected, in counts and bytes. nbytes comes off the
        # aval (no device sync); tracer-wrapped handles count too but die
        # with the trace. Plain list adds — this is the hot path. A handle
        # created while telemetry is off carries the None sentinel so a
        # later toggle can never skew the gauge negative.
        if _tm_memory.enabled():
            nb = int(getattr(data, "nbytes", 0) or 0)
            self._live_bytes = nb
            _tm_memory.ndarray_created(nb)
        else:
            self._live_bytes = None

    def __del__(self):
        # interpreter shutdown may have torn the module down — never raise
        try:
            if self._live_bytes is not None:
                _tm_memory.ndarray_freed(self._live_bytes)
        except Exception:
            pass

    # -- core properties --------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return "default"

    @property
    def grad(self):
        return self._grad

    @property
    def handle(self):  # legacy compat: the jax array IS the handle
        return self._data

    def _set_data(self, new_data):
        """Swap the underlying buffer (functional mutation)."""
        self._data = new_data
        self._version += 1
        if self._live_bytes is not None:
            nb = int(getattr(new_data, "nbytes", 0) or 0)
            if nb != self._live_bytes:
                _tm_memory.ndarray_resized(nb - self._live_bytes)
                self._live_bytes = nb

    # -- sync / transfer (engine boundary) --------------------------------
    def wait_to_read(self):
        """Block until value ready; async errors raise here
        (reference: NDArray::WaitToRead ndarray.h:359)."""
        self._data.block_until_ready()
        return self

    wait_to_write = wait_to_read

    def asnumpy(self):
        return _np.asarray(self._data)

    def asscalar(self):
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __len__(self):
        return self.shape[0]

    def copyto(self, other):
        import jax

        if isinstance(other, NDArray):
            other._set_data(jax.device_put(self._data, other._ctx.jax_device()))
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device()), ctx=other)
        raise TypeError("copyto: expected NDArray or Context")

    def as_in_context(self, ctx):
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context

    def copy(self):
        return self.copyto(self._ctx)

    def detach(self):
        out = NDArray(self._data, ctx=self._ctx)
        return out

    def astype(self, dtype, copy=True):
        return invoke("Cast", (self,), {"dtype": _np.dtype(np_dtype(dtype)).name})

    def to_dlpack_for_read(self):
        """DLPack capsule view (reference: ndarray.py:2231 over
        3rdparty/dlpack — zero-copy tensor exchange with torch/numpy)."""
        return self._data.__dlpack__()

    def to_dlpack_for_write(self):
        """reference: ndarray.py to_dlpack_for_write. jax.Arrays are
        immutable, so writable export is a copy-on-write divergence: the
        consumer gets a writable host COPY of the data; writes do not
        alias back (README divergences)."""
        return _np.array(self._data, copy=True).__dlpack__()

    # -- autograd ---------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer (reference: python ndarray.py attach_grad
        -> MXAutogradMarkVariables c_api_ndarray.cc:257). With
        stype='row_sparse' the tape's (dense) accumulated gradient is cast
        to row_sparse at write-back, so `.grad` feeds sparse optimizer
        kernels — same stance as gluon Parameter grad_stype."""
        import jax.numpy as jnp

        if (stype or "default") not in ("default", "row_sparse"):
            raise MXNetError("attach_grad: unsupported grad stype %r "
                             "(default/row_sparse)" % (stype,))
        if stype == "row_sparse":
            # the grad buffer is row_sparse from the start so aliases taken
            # before backward stay valid (write-back mutates components)
            from . import sparse as _sparse

            self._grad = _sparse.zeros("row_sparse", self.shape,
                                       ctx=self._ctx,
                                       dtype=_np.dtype(self.dtype).name)
        else:
            # a 64-bit array's grad buffer must keep the wide dtype (the
            # default config would silently truncate the zeros to 32-bit)
            with _x64_if_wide(self._data):
                self._grad = NDArray(jnp.zeros(self.shape, self.dtype),
                                     ctx=self._ctx)
        self._grad_req = grad_req
        self._grad_stype = stype or "default"

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # -- shape ops --------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return invoke("Reshape", (self,), {"shape": shape,
                                           "reverse": kwargs.get("reverse", False)})

    def reshape_like(self, other):
        return invoke("Reshape", (self,), {"shape": other.shape})

    def transpose(self, axes=None):
        return invoke("transpose", (self,), {"axes": axes})

    @property
    def T(self):
        return self.transpose()

    def flatten(self):
        return invoke("Flatten", (self,), {})

    def expand_dims(self, axis):
        return invoke("expand_dims", (self,), {"axis": axis})

    def squeeze(self, axis=None):
        return invoke("squeeze", (self,), {"axis": axis})

    def swapaxes(self, dim1, dim2):
        return invoke("SwapAxis", (self,), {"dim1": dim1, "dim2": dim2})

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke("SliceChannel", (self,), {"num_outputs": num_outputs,
                                                "axis": axis,
                                                "squeeze_axis": squeeze_axis})

    def slice(self, begin, end, step=None):
        return invoke("slice", (self,), {"begin": begin, "end": end,
                                         "step": step or ()})

    def slice_axis(self, axis, begin, end):
        return invoke("slice_axis", (self,), {"axis": axis, "begin": begin, "end": end})

    def broadcast_to(self, shape):
        return invoke("broadcast_to", (self,), {"shape": shape})

    def broadcast_like(self, other):
        return invoke("broadcast_like", (self, other), {})

    def tile(self, reps):
        return invoke("tile", (self,), {"reps": reps})

    def repeat(self, repeats, axis=None):
        return invoke("repeat", (self,), {"repeats": repeats, "axis": axis})

    def take(self, indices, axis=0, mode="clip"):
        return invoke("take", (self, indices), {"axis": axis, "mode": mode})

    def pick(self, index, axis=-1, keepdims=False):
        return invoke("batch_take", (self, index), {"axis": axis, "keepdims": keepdims})

    def one_hot(self, depth, **kw):
        return invoke("one_hot", (self,), dict(depth=depth, **kw))

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sp

        return _sp.cast_storage(self, stype)

    # -- reductions -------------------------------------------------------
    def sum(self, axis=None, keepdims=False, **kw):
        return invoke("sum", (self,), {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False, **kw):
        return invoke("mean", (self,), {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False, **kw):
        return invoke("prod", (self,), {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False, **kw):
        return invoke("max", (self,), {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False, **kw):
        return invoke("min", (self,), {"axis": axis, "keepdims": keepdims})

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke("norm", (self,), {"ord": ord, "axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return invoke("argmax", (self,), {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return invoke("argmin", (self,), {"axis": axis, "keepdims": keepdims})

    def argsort(self, axis=-1, is_ascend=True):
        return invoke("argsort", (self,), {"axis": axis, "is_ascend": is_ascend})

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return invoke("topk", (self,), {"axis": axis, "k": k, "ret_typ": ret_typ,
                                        "is_ascend": is_ascend})

    def clip(self, a_min, a_max):
        return invoke("clip", (self,), {"a_min": a_min, "a_max": a_max})

    def abs(self):
        return invoke("abs", (self,), {})

    def sqrt(self):
        return invoke("sqrt", (self,), {})

    def square(self):
        return invoke("square", (self,), {})

    def exp(self):
        return invoke("exp", (self,), {})

    def log(self):
        return invoke("log", (self,), {})

    def sigmoid(self):
        return invoke("sigmoid", (self,), {})

    def tanh(self):
        return invoke("tanh", (self,), {})

    def relu(self):
        return invoke("relu", (self,), {})

    def softmax(self, axis=-1):
        return invoke("softmax", (self,), {"axis": axis})

    def log_softmax(self, axis=-1):
        return invoke("log_softmax", (self,), {"axis": axis})

    def dot(self, other, transpose_a=False, transpose_b=False):
        return invoke("dot", (self, other), {"transpose_a": transpose_a,
                                             "transpose_b": transpose_b})

    def zeros_like(self):
        return invoke("zeros_like", (self,), {})

    def ones_like(self):
        return invoke("ones_like", (self,), {})

    def flip(self, axis):
        return invoke("reverse", (self,), {"axis": axis})

    def pad(self, mode="constant", pad_width=(), constant_value=0.0):
        return invoke("Pad", (self,), {"mode": mode, "pad_width": pad_width,
                                       "constant_value": constant_value})

    # -- arithmetic dunders ----------------------------------------------
    def _binary(self, other, op, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            args = (other, self) if reverse else (self, other)
            return invoke(op, args, {})
        if isinstance(other, numeric_types):
            name = scalar_op
            if reverse and "_r" not in scalar_op:
                rname = scalar_op.replace("_scalar", "").replace("_", "", 1)
                name = "_r%s_scalar" % rname
                if name not in _ops._REGISTRY:
                    name = scalar_op  # commutative
            return invoke(name, (self,), {"scalar": float(other)})
        if isinstance(other, _np.ndarray):
            return self._binary(array(other, ctx=self._ctx), op, scalar_op, reverse)
        return NotImplemented

    def __add__(self, o):
        return self._binary(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binary(o, "elemwise_sub", "_rminus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elemwise_div", "_div_scalar")

    def __rtruediv__(self, o):
        return self._binary(o, "elemwise_div", "_rdiv_scalar", reverse=True)

    def __mod__(self, o):
        return self._binary(o, "elemwise_mod", "_mod_scalar")

    def __rmod__(self, o):
        return self._binary(o, "elemwise_mod", "_rmod_scalar", reverse=True)

    def __pow__(self, o):
        return self._binary(o, "elemwise_power", "_power_scalar")

    def __rpow__(self, o):
        return self._binary(o, "elemwise_power", "_rpower_scalar", reverse=True)

    def __matmul__(self, o):
        return self.dot(o)

    def __neg__(self):
        return invoke("negative", (self,), {})

    def __abs__(self):
        return invoke("abs", (self,), {})

    def __eq__(self, o):
        if isinstance(o, (NDArray,) + numeric_types):
            return self._binary(o, "elemwise_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, o):
        if isinstance(o, (NDArray,) + numeric_types):
            return self._binary(o, "elemwise_not_equal", "_not_equal_scalar")
        return NotImplemented

    def __gt__(self, o):
        return self._binary(o, "elemwise_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "elemwise_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "elemwise_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "elemwise_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    # in-place: functional buffer swap
    def __iadd__(self, o):
        self._set_data((self + o)._data)
        return self

    def __isub__(self, o):
        self._set_data((self - o)._data)
        return self

    def __imul__(self, o):
        self._set_data((self * o)._data)
        return self

    def __itruediv__(self, o):
        self._set_data((self / o)._data)
        return self

    # -- indexing ---------------------------------------------------------
    def _index_dtype(self):
        # int64 index arrays when any dim exceeds int32-max (cast must
        # happen inside the x64 scope or astype itself truncates)
        return "int64" if any(d > _INT32_MAX for d in self.shape) else "int32"

    def __getitem__(self, key):
        with _x64_if_large(self.shape):
            if isinstance(key, NDArray):
                key = key._data.astype(self._index_dtype())
            out = self._data[key]
        return NDArray(out, ctx=self._ctx)

    def __setitem__(self, key, value):
        import jax.numpy as jnp

        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, _np.ndarray):
            value = jnp.asarray(value, dtype=self.dtype)
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            if not hasattr(value, "shape") or value.shape != self.shape:
                value = jnp.broadcast_to(jnp.asarray(value, dtype=self.dtype), self.shape)
            self._set_data(jnp.asarray(value, dtype=self.dtype))
        else:
            with _x64_if_large(self.shape):
                if isinstance(key, NDArray):
                    key = key._data.astype(self._index_dtype())
                self._set_data(self._data.at[key].set(value))

    def __iter__(self):
        for i in range(self.shape[0]):
            yield self[i]

    def __repr__(self):
        return "\n%s\n<NDArray %s @%s>" % (
            self.asnumpy(), "x".join(str(s) for s in self.shape), self._ctx)

    # numpy interop
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a


# --------------------------------------------------------------------------
# op invocation — the Imperative::Invoke equivalent
# --------------------------------------------------------------------------

_IS_TRAIN_CACHE = {}


def _takes_is_train(opdef):
    v = _IS_TRAIN_CACHE.get(opdef.name)
    if v is None:
        try:
            # any param named is_train counts, incl. keyword-only
            # (Custom declares it after *arrays)
            v = "is_train" in inspect.signature(opdef.fn).parameters
        except (TypeError, ValueError):
            v = False
        _IS_TRAIN_CACHE[opdef.name] = v
    return v


def invoke(op_name, inputs, attrs, out=None):
    """Invoke a registered op on NDArrays (reference call path:
    MXImperativeInvokeEx c_api_ndarray.cc:132 -> Imperative::Invoke
    imperative.cc:89 -> PushFCompute; here: resolve -> compiled-exec cache ->
    wrap -> tape record)."""
    from .. import autograd, random as _random

    opdef = _ops.get(op_name)
    attrs = {k: v for k, v in attrs.items() if v is not None or k in ("axis",)}
    attrs.pop("name", None)
    attrs.pop("dtype_np", None)
    if opdef.host:
        # host-side op (reference CPU-only FComputeEx analogue): fn
        # takes/returns NDArray-level objects eagerly — never jitted,
        # never on the tape (the reference registers no gradient either)
        from .. import profiler as _profiler

        hargs = ((_random.next_key(),) if opdef.needs_rng else ()) \
            + tuple(inputs)
        results = _profiler.timed_call(op_name, lambda a: opdef.fn(*a, **attrs),
                                       (hargs,))
        if isinstance(results, (tuple, list)) and len(results) == 1:
            return results[0]
        return list(results) if isinstance(results, (tuple, list)) \
            else results
    if _takes_is_train(opdef):
        attrs.setdefault("is_train", autograd.is_training())

    in_arrays = tuple(i._data if isinstance(i, NDArray) else i for i in inputs)
    rng = _random.next_key() if opdef.needs_rng else None
    call_arrays = (rng,) + in_arrays if opdef.needs_rng else in_arrays

    from .. import profiler as _profiler

    # the ProfileOperator hook (reference: graph_executor.cc:1309 wraps each
    # pushed op when profiling is enabled)
    # numeric attrs can also demand large-tensor mode: a `shape` whose
    # output exceeds int32-max (scatter_nd / init ops), or any attr the
    # opdef declares size-bearing (range_max, one_hot depth, Embedding
    # input_dim, arange stop — OpDef.size_attrs) whose magnitude creates
    # an index space past int32-max
    attr_shape = attrs.get("shape", ())
    if not (isinstance(attr_shape, (tuple, list))
            and all(isinstance(d, (int, _np.integer)) for d in attr_shape)):
        attr_shape = ()
    import math as _math

    bounds = tuple((int(abs(attrs[k])),) for k in opdef.size_attrs
                   if isinstance(attrs.get(k), (int, float, _np.integer,
                                                _np.floating))
                   and not isinstance(attrs.get(k), bool)
                   and _math.isfinite(attrs[k]))
    # dtype-triggered arm as well: a float64 operand (argmax index past
    # int32-max) silently narrows at trace time if only shapes are
    # consulted. jax.jit keys on avals, so armed/unarmed traces of the
    # same op never collide.
    with _x64_arming(arrays=in_arrays,
                     shapes=(attr_shape, *bounds,
                             *(a.shape for a in in_arrays
                               if hasattr(a, "shape"))))[0]:
        results = _profiler.timed_call(op_name, _ops.invoke_jax,
                                       (op_name, call_arrays, attrs))
    multi = isinstance(results, (tuple, list))
    results = tuple(results) if multi else (results,)

    ctx = None
    for i in inputs:
        if isinstance(i, NDArray):
            ctx = i._ctx
            break
    ctx = ctx or current_context()
    out_nd = [NDArray(r, ctx=ctx) for r in results]

    # aux write-back: trailing (num_outputs - visible) outputs map onto the
    # trailing inputs (BatchNorm moving stats, optimizer states)
    n_aux = (opdef.num_outputs - opdef.visible_outputs) if opdef.num_outputs > 0 else 0
    if n_aux > 0:
        aux_inputs = [i for i in inputs if isinstance(i, NDArray)][-n_aux:]
        for dst, src in zip(aux_inputs, results[-n_aux:]):
            dst._set_data(src)
        out_nd = out_nd[: opdef.visible_outputs]

    if autograd.is_recording():
        autograd._record(opdef, attrs, rng, inputs, in_arrays, out_nd, results)

    if out is not None:
        outs = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(outs, out_nd):
            dst._set_data(src._data)
        return out

    if len(out_nd) == 1:
        return out_nd[0]
    return out_nd


# --------------------------------------------------------------------------
# creation / io functions (reference: python/mxnet/ndarray/ndarray.py + utils)
# --------------------------------------------------------------------------

def from_jax(arr, ctx=None):
    return NDArray(arr, ctx=ctx)


def array(source, ctx=None, dtype=None):
    import jax
    import jax.numpy as jnp

    ctx = ctx or current_context()
    if isinstance(source, NDArray):
        if dtype is None:
            dtype = source.dtype  # reference keeps NDArray dtype
        source = source._data
    if dtype is None:
        # reference default: float32 for any non-NDArray source
        # (python/mxnet/ndarray/ndarray.py `array`)
        dtype = "float32"
    npa = _np.asarray(source, dtype=np_dtype(dtype))
    if npa.dtype in (_np.int64, _np.uint64) and npa.size and \
            not jax.config.jax_enable_x64:
        # int64 policy (README divergences): device integers are int32
        # (XLA's native index type) under default config. Narrowing is
        # silent for in-range values; out-of-range values would corrupt
        # silently, so raise with the escape hatch instead.
        lo, hi = int(npa.min()), int(npa.max())
        if lo < -2 ** 31 or hi >= 2 ** 31:
            raise MXNetError(
                "int64 values out of int32 range (%d..%d): device arrays "
                "narrow to int32 under default JAX config; set "
                "JAX_ENABLE_X64=1 for true int64, or keep large ids on "
                "host-side paths (recordio keys, dgl graph ops)"
                % (lo, hi))
    return NDArray(jax.device_put(jnp.asarray(npa), ctx.jax_device()), ctx=ctx)


def zeros(shape, ctx=None, dtype="float32", **kwargs):
    import jax
    import jax.numpy as jnp

    ctx = ctx or current_context()
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jax.device_put(jnp.zeros(shape, np_dtype(dtype)), ctx.jax_device()), ctx=ctx)


def ones(shape, ctx=None, dtype="float32", **kwargs):
    import jax
    import jax.numpy as jnp

    ctx = ctx or current_context()
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jax.device_put(jnp.ones(shape, np_dtype(dtype)), ctx.jax_device()), ctx=ctx)


def full(shape, val, ctx=None, dtype="float32", **kwargs):
    import jax
    import jax.numpy as jnp

    ctx = ctx or current_context()
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(jax.device_put(jnp.full(shape, val, np_dtype(dtype)), ctx.jax_device()), ctx=ctx)


def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    out = invoke("_arange", (), {"start": start, "stop": stop, "step": step,
                                 "repeat": repeat, "dtype": dtype})
    if ctx is not None:
        return out.as_in_context(ctx)
    return out


def concat(*arrays, dim=1):
    return invoke("Concat", tuple(arrays), {"dim": dim})


def stack(*arrays, axis=0):
    return invoke("stack", tuple(arrays), {"axis": axis})


def waitall():
    from .. import engine

    engine.wait_all()


def save(fname, data):
    """Save NDArrays (reference format: prefix.params via NDArray::Save
    src/ndarray/ndarray.cc; ours is an npz container — same keys/roundtrip,
    different binary layout, documented divergence).

    Crash-consistent: the npz is written to a same-directory temp file,
    fsynced, and atomically renamed onto `fname` — a worker killed mid-save
    (the fault-tolerance layer's failure model, docs/fault_tolerance.md)
    never leaves a truncated `.params` file, only either the old complete
    file or the new one. Every checkpoint path (`model.save_checkpoint`,
    `Block.save_parameters`, `Module.save_params`) funnels through here."""
    from ..base import atomic_writer

    if isinstance(data, NDArray):
        data = {"0": data}
    if isinstance(data, (list, tuple)):
        data = {str(i): v for i, v in enumerate(data)}
    arrays = {k: v.asnumpy() if isinstance(v, NDArray) else _np.asarray(v)
              for k, v in data.items()}
    # write through a file object: savez then cannot append ".npz" to the
    # name, so the rename target is exactly the requested filename
    with atomic_writer(fname, "wb") as f:
        _np.savez(f, **arrays)


class _DLPackCapsule:
    """Adapter: modern jax/numpy from_dlpack want the protocol object, but
    the reference API (and our to_dlpack_for_*) hands around raw PyCapsules
    (ndarray.py:2231). Wraps a capsule as a one-shot protocol object;
    capsules carry no device tag, so host (kDLCPU) is assumed — the only
    transport the reference's dlpack path supports either."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, **_kwargs):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)  # kDLCPU


def from_dlpack(capsule_or_tensor):
    """Build an NDArray from a DLPack capsule or any object with
    ``__dlpack__`` (torch tensors, numpy arrays, jax arrays) —
    reference: ndarray.py from_dlpack."""
    import jax.numpy as jnp

    obj = capsule_or_tensor
    if not hasattr(obj, "__dlpack__"):
        obj = _DLPackCapsule(obj)
    return array(jnp.from_dlpack(obj))


def to_dlpack_for_read(arr):
    """Module-level form (reference exports both)."""
    return arr.to_dlpack_for_read()


def to_dlpack_for_write(arr):
    return arr.to_dlpack_for_write()


def load(fname):
    from ..base import MXNetError

    src = fname
    if isinstance(fname, (bytes, bytearray)):
        # in-memory load (reference: MXNDListCreate takes raw file bytes)
        import io

        src = io.BytesIO(bytes(fname))
        fname = "<bytes>"
    import zipfile
    import zlib

    try:
        with _np.load(src, allow_pickle=False) as f:
            # preserve the on-disk dtype: array() defaults to float32, which
            # would silently upcast e.g. offline-quantized int8 params
            out = {k: array(f[k], dtype=f[k].dtype) for k in f.files}
    except (zipfile.BadZipFile, EOFError, zlib.error) as e:
        # ONLY the actual truncation/corruption signatures get the
        # corruption diagnosis — other errors (allow_pickle refusals,
        # IO/permission problems) keep their original meaning
        raise MXNetError(
            "failed to load NDArrays from %r: file is truncated or corrupt "
            "(%s: %s). nd.save writes atomically (temp + rename), so a "
            "complete save can't produce this — the file was likely copied "
            "partially, written by an interrupted transfer, or predates the "
            "atomic-save format. Restore from the previous checkpoint "
            "(CheckpointManager.latest() skips corrupt steps automatically)."
            % (fname, type(e).__name__, e)) from e
    keys = list(out)
    if keys and all(k.isdigit() for k in keys):
        return [out[k] for k in sorted(keys, key=int)]
    return out
