"""Deployment predictor.

TPU-native equivalent of the reference's C predict API
(include/mxnet/c_predict_api.h — 17 functions: MXPredCreate,
MXPredSetInput, MXPredForward, MXPredGetOutput, MXPredReshape,
MXPredPartialOut, MXPredFree; src/c_api/c_predict_api.cc). The surface is a
`Predictor` class whose methods map 1:1 onto those entry points; it loads
the `prefix-symbol.json` + `prefix-0000.params` artifacts produced by
`HybridBlock.export` / `model.save_checkpoint` and runs inference through
the jit-compiled Executor — one XLA executable per input signature, cached
across calls (the predict API's raison d'être: cheap repeated forward).
"""
from __future__ import annotations

import os as _os

import numpy as _np

from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError
from .context import current_context

__all__ = ["Predictor", "CompiledPredictor", "load_ndarray_file"]


def load_ndarray_file(nd_bytes_or_file):
    """reference: MXNDListCreate c_predict_api.h — load a saved NDArray
    dict/list for feeding a predictor."""
    return nd.load(nd_bytes_or_file)


class Predictor:
    """reference: MXPredCreate/MXPredCreatePartialOut (c_predict_api.h).

    Parameters
    ----------
    symbol_file : path to prefix-symbol.json (or a Symbol)
    param_file : path to prefix-%04d.params
    ctx : device context
    input_shapes : dict name -> shape (batch included)
    output_names : optional internal-output selection (PartialOut parity)
    """

    def __init__(self, symbol_file, param_file=None, ctx=None,
                 input_shapes=None, output_names=None, input_dtypes=None):
        self._ctx = ctx or current_context()
        if isinstance(symbol_file, sym_mod.Symbol):
            symbol = symbol_file
        elif isinstance(symbol_file, str) and symbol_file.lstrip()[:1] == "{":
            # a JSON string rather than a path (MXPredCreate passes the
            # symbol json by value — c_predict_api.h:78 symbol_json_str)
            symbol = sym_mod.load_json(symbol_file)
        else:
            symbol = sym_mod.load(symbol_file)
        if output_names:
            internals = symbol.get_internals()
            outs = internals.list_outputs()
            picked = []
            for name in output_names:
                if name not in outs:
                    raise MXNetError("output '%s' not in graph (have %s...)"
                                     % (name, outs[:10]))
                picked.append(internals[outs.index(name)])
            symbol = sym_mod.Group(picked)
        self._symbol = symbol
        self._arg_params, self._aux_params = {}, {}
        if param_file is not None:
            from .model import load_params

            self._arg_params, self._aux_params = load_params(param_file)
        if not input_shapes:
            raise MXNetError("input_shapes is required (as in MXPredCreate)")
        self._input_shapes = {k: tuple(v) for k, v in input_shapes.items()}
        # declared input dtypes (default float32, the reference predict
        # API's only dtype — c_predict_api.h mx_float); int inputs
        # (embedding token ids) are declared here so the bound buffer,
        # set_input casts and the AOT export contract all agree
        self._input_dtypes = {k: _np.dtype(_np.float32)
                              for k in self._input_shapes}
        self._input_dtypes.update(
            {k: _np.dtype(v) for k, v in (input_dtypes or {}).items()})
        self._inputs = {}
        self._outputs = None
        self._bind()

    def _bind(self, shared=None):
        """shared: another Predictor whose non-input device buffers
        (weights + aux) this one reuses — the reference's
        MXPredCreateMultiThread / MXPredReshape semantics
        (c_predict_api.cc:216,347 share weights across executors;
        only input/output buffers are private)."""
        args = {}
        for name in self._symbol.list_arguments():
            if name in self._input_shapes:
                args[name] = nd.zeros(self._input_shapes[name],
                                      ctx=self._ctx,
                                      dtype=self._input_dtypes[name])
            elif shared is not None and name in shared._args:
                args[name] = shared._args[name]
            elif name in self._arg_params:
                args[name] = self._arg_params[name].as_in_context(self._ctx)
            else:
                raise MXNetError(
                    "argument '%s' has neither a param nor an input shape"
                    % name)
        if shared is not None:
            aux = shared._aux_bound
        else:
            aux = {k: v.as_in_context(self._ctx)
                   for k, v in self._aux_params.items()}
        self._aux_bound = aux
        self._exe = self._symbol.bind(self._ctx, args=args, grad_req="null",
                                      aux_states=aux)
        self._args = args

    # -- the c_predict_api surface ----------------------------------------
    def set_input(self, name, data):
        """reference: MXPredSetInput."""
        if name not in self._input_shapes:
            raise MXNetError("'%s' is not an input (inputs: %s)"
                             % (name, sorted(self._input_shapes)))
        arr = data if isinstance(data, nd.NDArray) else \
            nd.array(_np.asarray(data, dtype=self._input_dtypes[name]),
                     ctx=self._ctx)
        if tuple(arr.shape) != self._input_shapes[name]:
            raise MXNetError("input '%s' shape %s != declared %s (use "
                             "reshape())" % (name, arr.shape,
                                             self._input_shapes[name]))
        self._args[name]._set_data(arr.as_in_context(self._ctx)._data)

    def forward(self, **kwargs):
        """reference: MXPredForward (kwargs are a set_input shorthand)."""
        for k, v in kwargs.items():
            self.set_input(k, v)
        self._outputs = self._exe.forward(is_train=False)
        return self

    def get_output(self, index=0):
        """reference: MXPredGetOutput."""
        if self._outputs is None:
            raise MXNetError("forward() has not been called")
        return self._outputs[index]

    @property
    def num_outputs(self):
        return len(self._symbol.list_outputs())

    def get_output_shape(self, index=0):
        """reference: MXPredGetOutputShape."""
        _, out_shapes, _ = self._symbol.infer_shape(**self._input_shapes)
        return out_shapes[index]

    def reshape(self, new_input_shapes):
        """reference: MXPredReshape — rebind for new input geometry (the
        executable cache keeps previously-compiled signatures warm)."""
        self._input_shapes.update(
            {k: tuple(v) for k, v in new_input_shapes.items()})
        self._bind()
        return self

    def free(self):
        """reference: MXPredFree (a no-op beyond dropping references —
        buffers are garbage-collected)."""
        self._exe = None
        self._outputs = None

    def export_compiled(self, path=None):
        """Build the AOT deployment artifact (TensorRT-engine analogue —
        see CompiledPredictor above): serialize the full forward as
        StableHLO with parameters frozen in as constants. Returns the
        bytes; writes them to `path` when given. Reload with
        `CompiledPredictor.load` (or raw jax.export.deserialize)."""
        import json as _json

        import jax
        import jax.export

        names = sorted(self._input_shapes)
        consts = {k: v._data for k, v in self._args.items()
                  if k not in self._input_shapes}
        consts.update({k: v.as_in_context(self._ctx)._data
                       for k, v in self._aux_params.items()})

        def fwd(*data_vals):
            vals = dict(consts)
            vals.update(zip(names, data_vals))
            # fixed key: inference graphs must not split the global RNG
            # chain inside the export trace (tracer leak), and an AOT
            # artifact should be deterministic anyway
            outs, _ = self._symbol._interpret(
                vals, is_train=False, rng_key=jax.random.PRNGKey(0))
            return tuple(outs)

        # trace each input at its DECLARED dtype (int32 token ids for
        # embedding models, not a blanket float32) so the AOT artifact's
        # input contract matches the live Predictor's
        in_dtypes = {n: self._input_dtypes[n].name for n in names}
        structs = [jax.ShapeDtypeStruct(self._input_shapes[n],
                                        _np.dtype(in_dtypes[n]))
                   for n in names]
        # one-shot export trace: the jit exists only to feed
        # jax.export and the result is persisted as an AOT artifact, so
        # there is no live cache to route through the compile registry
        exported = jax.export.export(
            jax.jit(fwd), platforms=_export_platforms())(*structs)  # mxlint: disable=retrace-hazard
        out_shapes = [tuple(a.shape) for a in exported.out_avals]
        header = _json.dumps({
            "input_names": names,
            "input_shapes": {n: list(self._input_shapes[n]) for n in names},
            "input_dtypes": in_dtypes,
            "output_shapes": [list(s) for s in out_shapes],
            "platforms": list(exported.platforms),
        }).encode()
        blob = (_MXC_MAGIC + len(header).to_bytes(8, "little") + header
                + bytes(exported.serialize()))
        if path is not None:
            with open(path, "wb") as f:
                f.write(blob)
        return blob


# ---------------------------------------------------------------------------
# AOT-compiled deployment artifacts (the TensorRT-integration analogue).
#
# The reference partitions inference graphs into TensorRT engines —
# ahead-of-time optimized, weights frozen, loadable without the training
# framework (src/executor/trt_graph_executor.cc:81, onnx_to_tensorrt.cc).
# The TPU-native equivalent is jax.export: the whole bound forward is
# lowered to StableHLO with parameters baked in as constants (XLA plays
# TensorRT's role as the optimizing engine), serialized to one portable
# artifact targeting cpu+tpu, and reloadable by `CompiledPredictor` — or by
# plain jax.export.deserialize, no model code needed.
# ---------------------------------------------------------------------------

_MXC_MAGIC = b"MXTPUAOT1\n"


def _export_platforms():
    """cpu + tpu so an artifact built on a CPU host runs on the chip."""
    import jax

    plats = {"cpu", "tpu"}
    plats.add(jax.default_backend())
    return tuple(sorted(plats))


class CompiledPredictor:
    """A deserialized AOT artifact with the Predictor calling surface
    (set_input/forward/get_output — the predict-API shape, c_predict_api.h),
    minus reshape: like a TensorRT engine, geometry is frozen at build."""

    def __init__(self, exported, input_names, input_shapes, output_shapes,
                 input_dtypes=None):
        self._exported = exported
        self._input_names = list(input_names)
        self._input_shapes = {k: tuple(v) for k, v in input_shapes.items()}
        self._input_dtypes = {k: _np.dtype(v)
                              for k, v in (input_dtypes or {}).items()}
        self._output_shapes = [tuple(s) for s in output_shapes]
        self._inputs = {}
        self._outputs = None

    @staticmethod
    def load(path_or_bytes):
        import json as _json

        import jax.export

        raw = path_or_bytes
        if isinstance(raw, (str, _os.PathLike)):
            # os.fspath: pathlib.Path artifacts load like str paths instead
            # of falling through to the bad-magic branch below
            with open(_os.fspath(raw), "rb") as f:
                raw = f.read()
        if not raw.startswith(_MXC_MAGIC):
            raise MXNetError("not a compiled predictor artifact (bad magic)")
        raw = raw[len(_MXC_MAGIC):]
        hlen = int.from_bytes(raw[:8], "little")
        header = _json.loads(raw[8:8 + hlen].decode())
        exported = jax.export.deserialize(bytearray(raw[8 + hlen:]))
        return CompiledPredictor(exported, header["input_names"],
                                 header["input_shapes"],
                                 header["output_shapes"],
                                 header.get("input_dtypes"))

    def set_input(self, name, data):
        if name not in self._input_shapes:
            raise MXNetError("'%s' is not an input (inputs: %s)"
                             % (name, self._input_names))
        arr = _np.asarray(data.asnumpy() if hasattr(data, "asnumpy")
                          else data,
                          dtype=self._input_dtypes.get(name, _np.float32))
        if tuple(arr.shape) != self._input_shapes[name]:
            raise MXNetError("input '%s' shape %s != frozen %s (AOT "
                             "artifacts have TensorRT-engine semantics: "
                             "rebuild for new geometry)"
                             % (name, arr.shape, self._input_shapes[name]))
        self._inputs[name] = arr
        return self

    def forward(self, **kwargs):
        from . import ndarray as nd

        for k, v in kwargs.items():
            self.set_input(k, v)
        missing = [n for n in self._input_names if n not in self._inputs]
        if missing:
            raise MXNetError("inputs not set: %s" % missing)
        outs = self._exported.call(*[self._inputs[n]
                                     for n in self._input_names])
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        self._outputs = [nd.array(_np.asarray(o)) for o in outs]
        return self

    def get_output(self, index=0):
        if self._outputs is None:
            raise MXNetError("forward() has not been called")
        return self._outputs[index]

    @property
    def num_outputs(self):
        return len(self._output_shapes)

    def get_output_shape(self, index=0):
        return self._output_shapes[index]

    @property
    def platforms(self):
        return self._exported.platforms


# ---------------------------------------------------------------------------
# Bridge functions for the native flat C ABI (mxnet_tpu/lib/src_capi/
# c_predict_api.cc — the reference's include/mxnet/c_predict_api.h surface).
# The C side passes/receives plain bytes + tuples so it never needs the
# numpy C API; all array handling stays here.
# ---------------------------------------------------------------------------

_DEVTYPE = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}


def _capi_create(symbol_json, param_bytes, dev_type, dev_id,
                 input_shapes, output_names=None):
    """reference: MXPredCreate / MXPredCreatePartialOut
    (src/c_api/c_predict_api.cc). dev_type uses the reference's encoding
    (1=cpu, 2=gpu — which resolves to the accelerator here, 6=tpu)."""
    from .context import Context

    ctx = Context(_DEVTYPE.get(int(dev_type), "cpu"), int(dev_id))
    return Predictor(symbol_json,
                     bytes(param_bytes) if param_bytes else None,
                     ctx=ctx, input_shapes=dict(input_shapes),
                     output_names=list(output_names) if output_names else None)


def _capi_set_input(pred, key, raw):
    shape = pred._input_shapes.get(key)
    if shape is None:
        raise MXNetError("'%s' is not an input (inputs: %s)"
                         % (key, sorted(pred._input_shapes)))
    n = int(_np.prod(shape)) if shape else 1
    arr = _np.frombuffer(raw, dtype=_np.float32)
    if arr.size != n:
        raise MXNetError("MXPredSetInput: size %d != declared %s (=%d floats)"
                         % (arr.size, shape, n))
    pred.set_input(key, arr.reshape(shape))


def _capi_forward(pred):
    pred.forward()


def _capi_get_output(pred, index):
    out = pred.get_output(int(index)).asnumpy()
    out = _np.ascontiguousarray(out, dtype=_np.float32)
    return out.tobytes(), tuple(int(d) for d in out.shape)


def _capi_output_shape(pred, index):
    return tuple(int(d) for d in pred.get_output_shape(int(index)))


def _clone_with(pred, input_shapes, shared):
    """New Predictor over the same symbol/params at `input_shapes`,
    optionally sharing `shared`'s device weight buffers."""
    new = Predictor.__new__(Predictor)
    new._ctx = pred._ctx
    new._symbol = pred._symbol
    new._arg_params = pred._arg_params
    new._aux_params = pred._aux_params
    new._input_shapes = dict(input_shapes)
    new._input_dtypes = dict(pred._input_dtypes)
    new._inputs = {}
    new._outputs = None
    new._bind(shared=shared)
    return new


def _capi_reshape(pred, input_shapes):
    """reference: MXPredReshape (c_predict_api.cc:347) — builds a NEW
    predictor at the new geometry sharing the original's weights; the
    handle passed in stays valid at its old shapes."""
    shapes = {k: tuple(v) for k, v in dict(input_shapes).items()}
    unknown = set(shapes) - set(pred._input_shapes)
    if unknown:
        raise MXNetError("MXPredReshape: %s are not inputs (inputs: %s)"
                         % (sorted(unknown), sorted(pred._input_shapes)))
    merged = dict(pred._input_shapes)
    merged.update(shapes)
    return _clone_with(pred, merged, shared=pred)


def _capi_clone_shared(pred):
    """reference: MXPredCreateMultiThread (c_predict_api.cc:216) — per-
    thread predictor sharing the prototype's weights; private IO buffers."""
    return _clone_with(pred, pred._input_shapes, shared=pred)


def _capi_ndlist(raw):
    """reference: MXNDListCreate — returns [(key, shape, float32-bytes)]."""
    loaded = load_ndarray_file(bytes(raw))
    items = loaded.items() if isinstance(loaded, dict) else \
        ((str(i), v) for i, v in enumerate(loaded))
    out = []
    for k, v in items:
        a = _np.ascontiguousarray(v.asnumpy(), dtype=_np.float32)
        out.append((k, tuple(int(d) for d in a.shape), a.tobytes()))
    return out
