"""Automatic symbol naming.

TPU-native equivalent of the reference's `python/mxnet/name.py`:
`NameManager` (auto `op0/op1/...` names, reference name.py:25) and `Prefix`
(prepends a prefix inside the scope, name.py:70). The symbol layer asks the
innermost manager for a name whenever the user didn't pass one.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]

_state = threading.local()


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = [NameManager()]
    return _state.stack


class NameManager:
    """Assigns unique names per op hint (reference: name.py:25)."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name:
            return name
        c = self._counter.get(hint, 0)
        self._counter[hint] = c + 1
        return "%s%d" % (hint, c)

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


class Prefix(NameManager):
    """NameManager adding a constant prefix (reference: name.py:70)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


def current():
    return _stack()[-1]
