"""Docstring-enhancement registry for generated Symbol functions
(reference: python/mxnet/symbol_doc.py — same scheme as ndarray_doc with
a Symbol-flavored layout)."""
from __future__ import annotations

from .ndarray_doc import _build_param_doc

__all__ = ["SymbolDoc", "_build_doc"]


class SymbolDoc:
    """Base class: subclasses named `<op>Doc` contribute extra doc.

    reference symbol_doc.py also exposed get_output_shape for doctests:"""

    @staticmethod
    def get_output_shape(sym, **input_shapes):
        """Infer and return output shapes keyed by output name."""
        _, s_outputs, _ = sym.infer_shape(**input_shapes)
        return dict(zip(sym.list_outputs(), s_outputs))


def _build_doc(func_name, desc, arg_names, arg_types, arg_desc,
               key_var_num_args=None, ret_type=None):
    """reference: symbol_doc.py _build_doc."""
    doc = "%s\n\n%s\nname : string, optional.\n" \
          "    Name of the resulting symbol.\n\n" \
          "Returns\n-------\n" \
          "Symbol\n    The result symbol.\n" \
          % (desc, _build_param_doc(arg_names, arg_types, arg_desc))
    extras = [cls.__doc__ for cls in type.__subclasses__(SymbolDoc)
              if cls.__name__ == "%sDoc" % func_name and cls.__doc__]
    if extras:
        doc += "\n" + "\n".join(extras)
    return doc
