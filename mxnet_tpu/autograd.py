"""Autograd: imperative gradient tape.

TPU-native equivalent of the reference's Imperative autograd
(src/imperative/imperative.cc: RecordOp :193, Backward :280; Python front
python/mxnet/autograd.py). The tape records every `invoke()` made inside a
`record()` scope as (opdef, attrs, inputs@version, outputs@version). Backward
walks the tape in reverse; each node's gradient is produced by a *cached,
jitted* `jax.vjp` of the same pure op function that ran forward — one
compiled backward kernel per (op, attrs), mirroring how the reference derives
backward nodes from the forward op's FGradient attr (nnvm/gradient.cc:271).

Versioned keys (NDArray._version) play the role of the reference's engine
variable versioning: in-place buffer swaps create a new logical node, keeping
the tape sound under mutation.

For throughput-critical training, hybridize (CachedOp) captures whole graphs
under one jit where XLA does AD-free fused codegen; this tape is the eager
path, like the reference's per-op Imperative::Backward.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as _np

from . import ops as _ops
from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "Function",
           "set_recording", "set_training"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []
        _state.freed = set()  # out_keys of nodes consumed by a prior backward
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(is_record):
    """Flag-style recording control (reference: MXAutogradSetIsRecording).
    Unlike the record() scope this must NOT reset the tape: the reference
    pause/resume idiom (pause-scope exit calls set_recording(prev)) resumes
    recording onto the SAME graph. Tape/freed cleanup instead happens when
    a backward fully drains the tape (_run_backward)."""
    st = _st()
    prev = st.recording
    st.recording = is_record
    return prev


def set_training(train_mode_):
    st = _st()
    prev = st.training
    st.training = train_mode_
    return prev


@contextlib.contextmanager
def _scope(recording=None, training=None):
    st = _st()
    prev_r, prev_t = st.recording, st.training
    if recording is not None:
        if recording and not prev_r:
            st.tape = []  # fresh outermost record scope starts a new tape
            st.freed = set()
        st.recording = recording
    if training is not None:
        st.training = training
    try:
        yield
    finally:
        st.recording, st.training = prev_r, prev_t


def record(train_mode=True):
    """Scope: record ops for autograd (reference: autograd.py:122)."""
    return _scope(recording=True, training=train_mode)


def pause(train_mode=False):
    """Scope: stop recording (reference: autograd.py:141)."""
    return _scope(recording=False, training=train_mode)


def train_mode():
    return _scope(training=True)


def predict_mode():
    return _scope(training=False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers (reference: autograd.py:197 -> imperative.cc:126)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        # the paired buffer's storage decides the write-back path (a
        # row_sparse buffer must not be overwritten by a dense _set_data)
        v._grad_stype = getattr(g, "stype", "default")


# --------------------------------------------------------------------------
# tape
# --------------------------------------------------------------------------

class _Node:
    __slots__ = ("opdef", "attr_key", "rng", "inputs", "in_arrays", "out_keys",
                 "out_shapes", "out_dtypes", "py_backward")

    def __init__(self, opdef, attr_key, rng, inputs, in_arrays, out_keys,
                 out_shapes, out_dtypes):
        self.opdef = opdef
        self.attr_key = attr_key
        self.rng = rng
        self.inputs = inputs        # list[(NDArray, version)]
        self.in_arrays = in_arrays  # jax arrays at call time
        self.out_keys = out_keys    # list[(id, version)] for ALL outputs
        self.out_shapes = out_shapes
        self.out_dtypes = out_dtypes
        self.py_backward = None


def _record(opdef, attrs, rng, inputs, in_arrays, out_nd, all_results):
    """Called from ndarray.invoke while recording (reference: RecordOp)."""
    from .ndarray.ndarray import NDArray

    st = _st()
    # positionally aligned with in_arrays: None marks a non-NDArray slot
    # (e.g. an optional array input passed as None), so backward cotangents
    # zip back onto the right arrays
    nd_inputs = [(i, i._version) if isinstance(i, NDArray) else None
                 for i in inputs]
    attr_key = tuple(sorted((k, _ops._hashable(v)) for k, v in attrs.items()))
    out_keys = [(o._uid, o._version) for o in out_nd]
    # aux outputs (written back into trailing inputs) count too: their
    # cotangents are zero but the vjp needs seeds of the right shape
    out_shapes = [r.shape for r in all_results]
    out_dtypes = [r.dtype for r in all_results]
    st.tape.append(_Node(opdef, attr_key, rng, nd_inputs, in_arrays, out_keys,
                         out_shapes, out_dtypes))


def _is_float(dt):
    return _np.issubdtype(_np.dtype(dt), _np.floating) or str(dt) == "bfloat16"


def _x64_for_arrays(arrays, dtypes=()):
    """Backward arm of the large-tensor policy: replaying a saved op with
    x64 off canonicalizes saved 64-bit operands to 32 bits and re-resolves
    device_int_dtype() to int32, so gradients through indexing at
    positions past 2^31 silently land at the wrong element. Delegates to
    the single policy authority (ndarray._x64_arming); `dtypes` lets
    callers arm on 64-bit OUTPUT dtypes too (argmax-style nodes whose
    zero cotangents must be built wide)."""
    from .ndarray.ndarray import _x64_arming

    return _x64_arming(arrays=arrays, dtypes=dtypes)


def _bwd_jitted(name, attr_key, has_rng, x64=False):
    # x64 joins the cache key only: the same (op, attrs) replayed in and
    # out of large-tensor mode must not share a trace
    """Jitted per-(op, attrs) backward: recompute forward + vjp in one fused
    executable (the tape-recompute formulation; XLA DCEs what the pullback
    doesn't need). Resolves through the unified registry
    (`mxnet_tpu.compile`, kind ``op_bwd``): counters, ``jit_compile``
    events, FLOP accounting and the persistent tier ride the fill hook,
    and Custom-op backwards carry the same ``custom-op:<op_type>``
    invalidation tag as their forwards."""
    from . import compile as _compile

    key = _ops.op_key(name, attr_key, kind="op_bwd").with_static_extra(
        (bool(has_rng), bool(x64)))

    def build():
        import jax

        opdef = _ops.get(name)
        kwargs = dict(attr_key)

        def bwd(rng, in_arrays, float_cots):
            def f(*args):
                call = (rng,) + args if has_rng else args
                out = opdef.fn(*call, **kwargs)
                return out if isinstance(out, (tuple, list)) else (out,)

            primals, pull = jax.vjp(f, *in_arrays)
            seeds = []
            fi = 0
            for p in primals:
                if _is_float(p.dtype):
                    seeds.append(float_cots[fi])
                    fi += 1
                else:
                    seeds.append(_np.zeros(p.shape, jax.dtypes.float0))
            return pull(tuple(seeds))

        return jax.jit(bwd)

    return _compile.get_or_build(key, build, label="_backward_" + name)


def _run_backward(heads, head_grads, retain_graph=False):
    import jax.numpy as jnp

    st = _st()
    cot = {}
    for h, hg in zip(heads, head_grads):
        key = (h._uid, h._version)
        if hg is not None:
            seed = hg._data if hasattr(hg, "_data") else hg
        else:
            # a 64-bit head needs its ones-seed built under x64 or the
            # seed silently narrows and the vjp rejects it
            h_ctx, _ = _x64_for_arrays([h._data])
            with h_ctx:
                seed = jnp.ones(h.shape, h.dtype)
        cot[key] = cot[key] + seed if key in cot else seed

    touched = {}
    consumed = set()
    for node in reversed(st.tape):
        if not any(k in cot for k in node.out_keys):
            continue
        consumed.add(id(node))
        # sparse inputs carry component dicts; their float-ness is the
        # value component's (custom Function nodes with sparse args)
        if not any(_is_float(a.dtype) if hasattr(a, "dtype")
                   else _is_float(a["data"].dtype) if isinstance(a, dict)
                   and "data" in a else False
                   for a in node.in_arrays):
            continue
        x64_ctx, x64 = _x64_for_arrays(node.in_arrays,
                                       dtypes=node.out_dtypes)
        if node.py_backward is not None:
            with x64_ctx:
                all_cots = []
                for k, shp, dt in zip(node.out_keys, node.out_shapes,
                                      node.out_dtypes):
                    c = cot.get(k)
                    all_cots.append(c if c is not None else jnp.zeros(shp, dt))
                grads = node.py_backward(all_cots)
            grads = grads if isinstance(grads, (tuple, list)) else (grads,)
            in_cots = [g._data if hasattr(g, "_data") else g for g in grads]
        else:
            rng = node.rng
            if rng is None:
                import jax

                rng = jax.random.PRNGKey(0)
            fn = _bwd_jitted(node.opdef.name, node.attr_key,
                             node.opdef.needs_rng, x64)
            with x64_ctx:
                float_cots = []
                for k, shp, dt in zip(node.out_keys + [None] * (len(node.out_shapes) - len(node.out_keys)),
                                      node.out_shapes, node.out_dtypes):
                    if not _is_float(dt):
                        continue
                    c = cot.get(k) if k is not None else None
                    float_cots.append(c if c is not None
                                      else jnp.zeros(shp, dt))
                from . import profiler as _profiler

                # the backward half of the ProfileOperator hook: each tape
                # node replays as one "_backward_<op>" event (the
                # reference's backward-op naming), sharing timed_call with
                # the forward dispatch sites
                in_cots = _profiler.timed_call(
                    "_backward_" + node.opdef.name, fn,
                    (rng, node.in_arrays, tuple(float_cots)))
        for pair, c in zip(node.inputs, in_cots):
            if pair is None:
                continue
            arr, ver = pair
            if c is None or (hasattr(c, "dtype") and str(c.dtype) == "float0"):
                continue
            key = (arr._uid, ver)
            cot[key] = cot[key] + c if key in cot else c
            touched[arr._uid] = arr

    # write accumulated grads into attached buffers (dedup: an array that
    # is both a head and an interior input must be written once, or
    # grad_req='add' double-accumulates)
    targets = dict(touched)
    targets.update((h._uid, h) for h in heads)
    for aid, arr in targets.items():
        if arr._grad is None or arr._grad_req == "null":
            continue
        total = None
        for (kid, ver), c in cot.items():
            if kid == aid:
                total = c if total is None else total + c
        if total is None:
            continue
        from .ndarray.ndarray import _x64_if_wide

        wide_ctx = _x64_if_wide(total, arr._grad._data
                                if hasattr(arr._grad, "_data") else None)
        if getattr(arr, "_grad_stype", "default") == "row_sparse":
            # sparse grad buffer (attach_grad(stype='row_sparse')): cast the
            # dense tape gradient to row_sparse at write-back so sparse
            # optimizer kernels see indices (gluon Trainer does the same
            # for Parameter grad_stype)
            from .ndarray.ndarray import NDArray

            with wide_ctx:
                dense = total.astype(arr._grad.dtype)
                if arr._grad_req == "add":
                    prev = arr._grad
                    prev_dense = prev.tostype("default")._data \
                        if getattr(prev, "stype", "default") != "default" \
                        else prev._data
                    dense = dense + prev_dense
            rsp = NDArray(dense, ctx=arr._ctx).tostype("row_sparse")
            g = arr._grad
            if getattr(g, "stype", "default") == "row_sparse":
                # preserve buffer identity: aliases taken before backward
                # (mark_variables pairs, executor grad arrays) stay live
                g._shape = rsp._shape
                g._data = rsp._data
                g._version += 1
            else:
                arr._grad = rsp
        elif arr._grad_req == "add":
            with wide_ctx:
                arr._grad._set_data(arr._grad._data
                                    + total.astype(arr._grad.dtype))
        else:
            with wide_ctx:
                arr._grad._set_data(total.astype(arr._grad.dtype))
        arr._fresh_grad = True
    # A cotangent that reached a key produced by a node consumed in an
    # EARLIER backward means this head shares a subgraph with an already-
    # freed graph — grads would silently stop at the boundary. Match the
    # reference's "graph already freed" error instead.
    if st.freed and (set(cot) & st.freed):
        raise MXNetError(
            "backward reached part of the graph that was freed by a previous "
            "backward call. Use retain_graph=True on the earlier backward, or "
            "call autograd.backward([...]) once with all heads.")
    if not retain_graph:
        # Consume only the subgraph this backward traversed; other heads
        # recorded in the same scope (e.g. per-device loss copies — the
        # `for l in losses: l.backward()` idiom) keep their nodes.
        remaining = []
        for n in st.tape:
            if id(n) in consumed:
                st.freed.update(n.out_keys)
            else:
                remaining.append(n)
        st.tape = remaining
        if not st.tape and not st.recording:
            # graph fully drained outside any recording: the freed-key set
            # has nothing left to guard (nothing on the tape can reach a
            # freed node) — reset it so flag-style training loops (the C
            # ABI's SetIsRecording idiom) don't grow it without bound, and
            # so recycled object ids can't spuriously match stale keys
            st.freed = set()
    return cot


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of heads wrt all attached-grad variables
    (reference: autograd.py:243 -> MXAutogradBackwardEx -> imperative.cc:280)."""
    if head_grads is None:
        head_grads = [None] * len(heads)
    _run_backward(heads, head_grads, retain_graph)


def _build_replay_scalar(heads, variables, head_grads):
    """Replay the current tape as a pure function of `variables` AND every
    other graph leaf, reducing the heads to the scalar
    sum_i <head_i, head_grad_i>. This is the functional form of the
    recorded graph that create_graph differentiates: the reference keeps
    its symbolic grad-graph attached for re-derivation (nnvm/gradient.cc);
    here the replay + jax.grad plays that role. Leaves are traced (not
    constants) so second-order cotangents flow back into the enclosing
    tape — e.g. gradient penalties reach layer weights. Custom-Function
    node outputs are the one exception (their forward isn't re-traceable);
    they stay constant.

    Returns (scalar_fn, leaf_arrays): scalar_fn takes
    (*var_values, *leaf_values); leaf_arrays are the NDArrays to feed."""
    import jax.numpy as jnp

    st = _st()
    tape = list(st.tape)
    var_keys = [(v._uid, v._version) for v in variables]
    head_keys = [(h._uid, h._version) for h in heads]
    hgs = [None if hg is None else
           (hg._data if hasattr(hg, "_data") else jnp.asarray(hg))
           for hg in head_grads]

    # prune to ancestors of the heads: unrelated branches recorded in the
    # same scope (other losses, metrics) must not be replayed or traced
    needed = set(head_keys)
    keep = []
    for node in reversed(tape):
        if not any(k in needed for k in node.out_keys):
            continue
        if node.opdef is None:
            raise MXNetError(
                "create_graph=True cannot differentiate through a custom "
                "Function / bridged op in the heads' graph (its forward is "
                "not re-traceable); compute that grad without create_graph")
        keep.append(node)
        needed.update((p[0]._uid, p[1]) for p in node.inputs
                      if p is not None)
    tape = list(reversed(keep))
    if st.freed and (needed & st.freed):
        # same guard as _run_backward: a freed shared subgraph would become
        # a silent constant here instead of contributing gradient
        raise MXNetError(
            "create_graph backward reached part of the graph that was "
            "freed by a previous backward call. Use retain_graph=True on "
            "the earlier backward.")

    produced = set()
    for node in tape:
        produced.update(node.out_keys)
    leaf_info = {}
    for node in tape:
        for pair, const in zip(node.inputs, node.in_arrays):
            if pair is None:
                continue
            arr, ver = pair
            k = (arr._uid, ver)
            if k not in produced and k not in var_keys \
                    and k not in leaf_info:
                leaf_info[k] = arr
    leaf_keys = list(leaf_info)
    leaf_arrays = [leaf_info[k] for k in leaf_keys]
    var_seeded = set(var_keys)

    def scalar_fn(*vals):
        env = dict(zip(var_keys + leaf_keys, vals))
        for node in tape:
            ins = [const if p is None else env.get((p[0]._uid, p[1]), const)
                   for p, const in zip(node.inputs, node.in_arrays)]
            kwargs = dict(node.attr_key)
            call = ((node.rng,) + tuple(ins) if node.opdef.needs_rng
                    else tuple(ins))
            out = node.opdef.fn(*call, **kwargs)
            out = tuple(out) if isinstance(out, (tuple, list)) else (out,)
            for k, o in zip(node.out_keys, out):
                # a variable's traced value stays authoritative: grads wrt
                # an intermediate differentiate from that point on, not
                # through its recomputation
                if k not in var_seeded:
                    env[k] = o
        total = jnp.zeros((), jnp.float32)
        for hk, hg in zip(head_keys, hgs):
            val = env.get(hk)
            if val is None:
                continue  # head independent of the recorded graph
            seed = hg if hg is not None else jnp.ones(val.shape, val.dtype)
            total = total + jnp.sum(val.astype(jnp.float32)
                                    * seed.astype(jnp.float32))
        return total

    return scalar_fn, leaf_arrays


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Return grads of heads wrt variables without touching .grad buffers
    (reference: autograd.py:270). With create_graph=True the returned grads
    are themselves recorded on the tape (via a replay of the recorded
    graph), so a further backward()/grad() differentiates through them —
    reference semantics for gradient penalties / higher-order grads."""
    from .ndarray.ndarray import NDArray

    if head_grads is None:
        head_grads = [None] * len(heads)
    if create_graph:
        scalar_fn, leaf_arrays = _build_replay_scalar(heads, variables,
                                                      head_grads)
        op = _ReplayGradFn(scalar_fn, n_vars=len(variables))
        # replaying the tape re-traces every saved op: large-tensor
        # operands need the same x64 arming the original forward had
        x64_ctx, _ = _x64_for_arrays(
            [getattr(a, "_data", a) for a in (*variables, *leaf_arrays)])
        with x64_ctx:
            outs = op(*variables, *leaf_arrays)
        return list(outs)
    retain = True if retain_graph is None else retain_graph
    cot = _run_backward(heads, head_grads, retain_graph=retain)
    outs = []
    for v in variables:
        total = None
        for (kid, ver), c in cot.items():
            if kid == v._uid:
                total = c if total is None else total + c
        if total is None:
            import jax.numpy as jnp

            total = jnp.zeros(v.shape, v.dtype)
        outs.append(NDArray(total, ctx=v._ctx))
    return outs


def get_symbol(x):
    raise MXNetError("autograd.get_symbol: use HybridBlock.export / Symbol API")


class Function:
    """Custom differentiable function (reference: autograd.py:365).

    Subclass and implement forward(self, *inputs) and backward(self, *ograds)
    operating on NDArrays; invoked with .__call__."""

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray

        st = _st()
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (tuple, list))
        outs = (outputs,) if single else tuple(outputs)
        if st.recording:
            fn_self = self
            node_inputs = [(i, i._version) for i in inputs if isinstance(i, NDArray)]
            node = _Node(None, (), None, node_inputs,
                         tuple(i._data for i in inputs if isinstance(i, NDArray)),
                         [(o._uid, o._version) for o in outs],
                         [o.shape for o in outs], [o.dtype for o in outs])
            node.py_backward = lambda cots: fn_self.backward(
                *[NDArray(c) for c in cots])
            st.tape.append(node)
        return outputs


class _ReplayGradFn(Function):
    """The differentiable-gradient op create_graph records: forward emits
    d(scalar)/d(variables); backward is the vjp of that gradient function
    (Hessian-vector product), both derived by jax from the tape replay."""

    def __init__(self, scalar_fn, n_vars):
        super().__init__()
        import jax

        # derived once per node (not per forward/backward call); cross-call
        # caching is impossible — each grad() records a fresh tape
        self._grad_fn = jax.grad(scalar_fn, argnums=tuple(range(n_vars)))
        self._n_vars = n_vars
        self._vals = None

    def forward(self, *all_nds):
        from .ndarray.ndarray import NDArray

        # snapshot call-time buffers: later in-place mutation of a variable
        # (optimizer step) must not change what the HVP differentiates
        self._vals = [v._data for v in all_nds]
        gvals = self._grad_fn(*self._vals)
        return tuple(NDArray(g.astype(v._data.dtype), ctx=v._ctx)
                     for g, v in zip(gvals, all_nds[:self._n_vars]))

    def backward(self, *ograds):
        import jax

        vals = self._vals
        _, pull = jax.vjp(self._grad_fn, *vals)
        cots = pull(tuple(o._data.astype(vals[i].dtype)
                          for i, o in enumerate(ograds)))
        # raw jax values (float0 for int leaves); _run_backward's
        # py_backward path accepts them and skips float0 cotangents
        return tuple(cots)
