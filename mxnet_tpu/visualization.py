"""Network visualization.

TPU-native equivalent of the reference's `python/mxnet/visualization.py`:
`print_summary` (layer table with shapes/params, reference
visualization.py:38) and `plot_network` (graphviz digraph, reference
visualization.py:204 — gated on graphviz being importable, exactly as the
reference gates it at call time).
"""
from __future__ import annotations

import json

from .base import MXNetError
from .symbol.symbol import Symbol

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=(.44, .64, .74, 1.)):
    """Print a layer-by-layer summary table (reference: visualization.py:38)."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    show_shape = False
    shape_dict = {}
    arg_shapes = {}
    if shape is not None:
        show_shape = True
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        shape_dict = dict(zip(internals.list_outputs(), out_shapes))
        in_shapes, _, aux_sh = symbol.infer_shape(**shape)
        arg_shapes = dict(zip(symbol.list_arguments(), in_shapes))
        arg_shapes.update(zip(symbol.list_auxiliary_states(), aux_sh))
        arg_shapes = {k: v for k, v in arg_shapes.items() if k not in shape}

    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(f, pos):
        line = ""
        for i, field in enumerate(f):
            line += str(field)
            line = line[: pos[i]]
            line += " " * (pos[i] - len(line))
        print(line)  # allow-print

    print("_" * line_length)  # allow-print
    print_row(fields, positions)
    print("=" * line_length)  # allow-print
    total_params = [0]

    def out_shape_of(name):
        for suffix in ("_output", ""):
            key = name + suffix
            if key in shape_dict:
                return shape_dict[key]
        return None

    nodes = list(symbol._topo())
    for node in nodes:
        if node.is_var:
            continue
        name = node.name
        op = node.op
        pre = [s.name for s, _ in node.inputs if not s.is_var]
        cur_param = 0
        if show_shape:
            import numpy as np

            for src, _ in node.inputs:
                if src.is_var and src.name in arg_shapes and arg_shapes[src.name]:
                    cur_param += int(np.prod(arg_shapes[src.name]))
        total_params[0] += cur_param
        out_shape = out_shape_of(name) if show_shape else None
        first_conn = pre[0] if pre else ""
        print_row(["%s (%s)" % (name, op), str(out_shape or ""), str(cur_param),
                   first_conn], positions)
        for p in pre[1:]:
            print_row(["", "", "", p], positions)
        print("_" * line_length)  # allow-print
    print("Total params: %d" % total_params[0])  # allow-print
    print("_" * line_length)  # allow-print


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 dtype=None, node_attrs=None, hide_weights=True):
    """Graphviz digraph of the network (reference: visualization.py:204).
    Requires the `graphviz` package, like the reference."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("plot_network requires the graphviz python package")
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")

    node_attrs = node_attrs or {}
    node_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)

    # palette per op family (reference uses the same scheme)
    def fill(op):
        if op is None:
            return "#8dd3c7"
        if op in ("Convolution", "Deconvolution", "FullyConnected"):
            return "#fb8072"
        if op in ("BatchNorm", "LayerNorm"):
            return "#bebada"
        if op in ("Activation", "LeakyReLU", "relu", "sigmoid", "tanh"):
            return "#ffffb3"
        if op in ("Pooling",):
            return "#80b1d3"
        if op in ("Concat", "Flatten", "Reshape"):
            return "#fdb462"
        if op in ("Softmax", "SoftmaxOutput", "softmax"):
            return "#fccde5"
        return "#b3de69"

    def looks_like_weight(name):
        return name.endswith(("_weight", "_bias", "_gamma", "_beta",
                              "_moving_mean", "_moving_var", "_running_mean",
                              "_running_var"))

    drawn = set()
    for node in symbol._topo():
        if node.is_var and hide_weights and looks_like_weight(node.name):
            continue
        label = node.name if node.is_var else "%s\n%s" % (node.op, node.name)
        dot.node(name=node.name, label=label,
                 **dict(node_attr, fillcolor=fill(node.op)))
        drawn.add(node.name)
    for node in symbol._topo():
        if node.name not in drawn:
            continue
        for src, _ in node.inputs:
            if src.name in drawn:
                dot.edge(tail_name=src.name, head_name=node.name)
    return dot
