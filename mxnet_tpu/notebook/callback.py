"""Notebook training callbacks (reference: python/mxnet/notebook/
callback.py). `PandasLogger` records train/eval/epoch metrics into
pandas DataFrames through the standard fit() callback slots; the
Live*Chart family needs bokeh (not installed here) and raises with a
clear message instead of half-rendering."""
from __future__ import annotations

import datetime
import time

try:
    import pandas as pd
except ImportError:  # pragma: no cover - pandas is baked into this image
    pd = None

__all__ = ["PandasLogger", "LiveBokehChart", "LiveLearningCurve"]


class PandasLogger:
    """reference: notebook/callback.py:71 — three DataFrames (train,
    eval, epoch); wire in with ``model.fit(**logger.callback_args())``."""

    def __init__(self, batch_size, frequent=50):
        if pd is None:
            raise ImportError("PandasLogger needs pandas")
        self.batch_size = batch_size
        self.frequent = frequent
        self._dataframes = {"train": pd.DataFrame(),
                            "eval": pd.DataFrame(),
                            "epoch": pd.DataFrame()}
        self.last_time = time.time()
        self.start_time = datetime.datetime.now()
        self.last_epoch_time = datetime.datetime.now()

    @property
    def train_df(self):
        return self._dataframes["train"]

    @property
    def eval_df(self):
        return self._dataframes["eval"]

    @property
    def epoch_df(self):
        return self._dataframes["epoch"]

    @property
    def all_dataframes(self):
        return self._dataframes

    def elapsed(self):
        return datetime.datetime.now() - self.start_time

    def append_metrics(self, metrics, df_name):
        df = self._dataframes[df_name]
        for col in set(metrics) - set(df.columns):
            df[col] = None
        df.loc[len(df)] = metrics

    def train_cb(self, param):
        if param.nbatch % self.frequent == 0:
            self._process_batch(param, "train")

    def eval_cb(self, param):
        self._process_batch(param, "eval")

    def _process_batch(self, param, df_name):
        now = time.time()
        if param.eval_metric is not None:
            metrics = dict(param.eval_metric.get_name_value())
            param.eval_metric.reset()
        else:
            metrics = {}
        try:
            speed = self.frequent / (now - self.last_time)
        except ZeroDivisionError:
            speed = float("inf")
        metrics["batches_per_sec"] = speed * self.batch_size
        metrics["records_per_sec"] = speed
        metrics["elapsed"] = self.elapsed()
        metrics["minibatch_count"] = param.nbatch
        metrics["epoch"] = param.epoch
        self.append_metrics(metrics, df_name)
        self.last_time = now

    def epoch_cb(self):
        now = datetime.datetime.now()
        self.append_metrics({"elapsed": self.elapsed(),
                             "epoch_time": now - self.last_epoch_time},
                            "epoch")
        self.last_epoch_time = now

    def callback_args(self):
        """kwargs for model.fit() wiring all three callbacks."""
        return {"batch_end_callback": self.train_cb,
                "eval_end_callback": self.eval_cb,
                "epoch_end_callback": self.epoch_cb}


def _needs_bokeh(name):
    raise ImportError(
        "%s renders live bokeh charts in a notebook; bokeh is not "
        "installed in this environment. PandasLogger records the same "
        "metrics into DataFrames for offline plotting." % name)


class LiveBokehChart:
    """reference: notebook/callback.py:204 — requires bokeh."""

    def __init__(self, *args, **kwargs):
        _needs_bokeh("LiveBokehChart")


class LiveLearningCurve(LiveBokehChart):
    """reference: notebook/callback.py — requires bokeh."""

    def __init__(self, *args, **kwargs):
        _needs_bokeh("LiveLearningCurve")
