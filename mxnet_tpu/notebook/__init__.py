"""Jupyter-notebook training instrumentation (reference:
python/mxnet/notebook/)."""
from . import callback  # noqa: F401
