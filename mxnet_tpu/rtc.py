"""Runtime kernel compilation (RTC) — TPU-native analogue of the
reference's NVRTC bridge.

Reference: python/mxnet/rtc.py:42 `CudaModule` / :173 `CudaKernel` over
src/common/rtc.cc:35-60 (NVRTC compile at runtime, kernels launched as
engine ops). The TPU equivalent of "hand me kernel source at runtime and
launch it on device arrays" is Pallas: `PallasModule` takes Python source
defining Pallas kernel functions (written over `Ref`s, with `jax`, `jnp`,
`pl` (jax.experimental.pallas) and `np` in scope), compiles it once, and
`get_kernel(...).launch(args, ctx, grid_dims, block_dims)` runs it through
`pl.pallas_call` — Mosaic-compiled on TPU, interpret mode elsewhere (same
split as ops/pallas_kernels.py).

The launch contract mirrors CudaKernel.launch (rtc.py:185):

- `signature` is a C-style parameter list, e.g.
  ``"const float *x, float *y, float alpha"``. Pointer parameters are
  device arrays; non-pointer parameters are scalars. A **non-const
  pointer is an output**: it is updated in place (the buffer is aliased
  into the kernel, as CUDA kernels mutate global memory in place).
- the kernel function's parameters correspond 1:1 to the signature:
  each pointer argument arrives as a block `Ref`; each scalar arrives as
  a (1,)-shaped `Ref` (read it as ``s_ref[0]`` — scalars ride small
  memory, the Pallas idiom for kernel parameters).
- `grid_dims` is the Pallas grid (CUDA gridDim); `block_dims` is the
  per-program block shape applied to the *leading* dimensions of every
  array argument (CUDA blockDim). Trailing 1s are ignored, so CUDA-style
  3-tuples like ``(1, 1, 1)`` work unchanged. With ``block_dims=None``
  each program sees whole arrays.

Example (the reference's axpy, rtc.py:46-59, in Pallas form)::

    source = '''
    def axpy(x_ref, y_ref, alpha_ref):
        y_ref[...] += alpha_ref[0] * x_ref[...]
    '''
    module = mx.rtc.PallasModule(source, exports=["axpy"])
    func = module.get_kernel("axpy", "const float *x, float *y, float alpha")
    x = mx.nd.ones((10,), ctx=mx.tpu(0))
    y = mx.nd.zeros((10,), ctx=mx.tpu(0))
    func.launch([x, y, 3.0], mx.tpu(0), (1, 1, 1), (10, 1, 1))
"""
from __future__ import annotations

import re

import numpy as _np

from .base import MXNetError

__all__ = ["PallasModule", "PallasKernel", "CudaModule"]

# reference: rtc.py:30 _DTYPE_CPP_TO_NP (plus bfloat16 — the TPU-native
# half precision; "__half" keeps meaning float16 for signature parity)
_DTYPE_CPP_TO_NP = {
    "float": _np.float32,
    "double": _np.float64,
    "__half": _np.float16,
    "bfloat16": "bfloat16",
    "uint8_t": _np.uint8,
    "int": _np.int32,
    "int32_t": _np.int32,
    "int8_t": _np.int8,
    "char": _np.int8,
    "int64_t": _np.int64,
}


def _parse_signature(signature):
    """reference: CudaModule.get_kernel rtc.py:112-171 — same C-style
    parameter grammar; returns [(name, dtype, is_ndarray, is_const)]."""
    pattern = re.compile(r"""^\s*(const)?\s*([\w_]+)\s*(\*)?\s*([\w_]+)\s*$""")
    args = []
    for param in signature.split(","):
        match = pattern.match(param)
        if not match:
            raise MXNetError(
                "Invalid function prototype \"%s\". Must be in the form of "
                "\"(const) type (*) name\"" % param)
        is_const, ctype, is_ptr, name = match.groups()
        if ctype not in _DTYPE_CPP_TO_NP:
            raise MXNetError("Unsupported kernel argument type %s" % param)
        args.append((name, _np.dtype(_DTYPE_CPP_TO_NP[ctype]),
                     bool(is_ptr), bool(is_const)))
    return args


def _trim(dims):
    """Drop trailing 1s (CUDA-style 3-tuples -> minimal Pallas rank)."""
    dims = tuple(int(d) for d in dims)
    while len(dims) > 1 and dims[-1] == 1:
        dims = dims[:-1]
    return dims


class PallasModule:
    """Compile Pallas kernel source at runtime (reference: CudaModule
    rtc.py:42; compile step analogue of src/common/rtc.cc:35-60)."""

    def __init__(self, source, options=(), exports=()):
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        if options:
            raise MXNetError("PallasModule does not take nvcc options "
                             "(got %s) — Pallas source is Python" %
                             (options,))
        self._namespace = {"jax": jax, "jnp": jnp, "pl": pl, "np": _np}
        try:
            code = compile(source, "<rtc.PallasModule>", "exec")
            exec(code, self._namespace)
        except SyntaxError as e:
            raise MXNetError("PallasModule source failed to compile: %s" % e)
        self._exports = tuple(exports)
        for name in self._exports:
            if not callable(self._namespace.get(name)):
                raise MXNetError("exported kernel '%s' is not defined by the "
                                 "source" % name)

    def get_kernel(self, name, signature):
        """reference: CudaModule.get_kernel rtc.py:112."""
        fn = self._namespace.get(name)
        if not callable(fn):
            raise MXNetError("kernel '%s' is not defined by the source "
                             "(defined: %s)" % (name, sorted(
                                 k for k, v in self._namespace.items()
                                 if callable(v) and not k.startswith("_")
                                 and k not in ("jax", "jnp", "pl", "np"))))
        if self._exports and name not in self._exports:
            raise MXNetError("kernel '%s' is not exported (exports=%s)"
                             % (name, list(self._exports)))
        return PallasKernel(fn, name, _parse_signature(signature))


class PallasKernel:
    """A compiled kernel (reference: CudaKernel rtc.py:173). Executables
    are cached per (shapes, grid, block) signature — repeated launches
    re-use the compiled Mosaic binary, matching the engine-op reuse of the
    reference's CUfunction."""

    def __init__(self, fn, name, args):
        self._fn = fn
        self._name = name
        self._args = args  # [(name, dtype, is_ndarray, is_const)]
        self._cache = {}

    def launch(self, args, ctx, grid_dims, block_dims=None, shared_mem=0):
        """reference: CudaKernel.launch rtc.py:185. Non-const pointer args
        are updated in place; their NDArrays get the new value. Returns the
        list of output NDArrays (in signature order)."""
        from .ndarray import NDArray
        from .ndarray import array as nd_array

        if shared_mem:
            raise MXNetError("shared_mem is CUDA-specific; Pallas manages "
                             "VMEM via block shapes")
        if len(args) != len(self._args):
            raise MXNetError("kernel '%s' takes %d arguments, got %d"
                             % (self._name, len(self._args), len(args)))
        grid = _trim(grid_dims)
        block = _trim(block_dims) if block_dims is not None else None

        jax_vals = []
        for val, (aname, dtype, is_nd, _c) in zip(args, self._args):
            if is_nd:
                if not isinstance(val, NDArray):
                    val = nd_array(_np.asarray(val, dtype=dtype), ctx=ctx)
                if val.dtype != dtype and str(val.dtype) != str(dtype):
                    raise MXNetError(
                        "arg '%s' expects dtype %s, got %s"
                        % (aname, dtype, val.dtype))
                jax_vals.append(val._data)
            else:
                jax_vals.append(_np.asarray([val], dtype=dtype))

        key = (grid, block,
               tuple((tuple(v.shape), str(v.dtype)) for v in jax_vals))
        run = self._cache.get(key)
        if run is None:
            run = self._build(grid, block, jax_vals)
            self._cache[key] = run
        results = run(*jax_vals)

        outs = []
        ri = iter(results)
        for val, (aname, dtype, is_nd, is_const) in zip(args, self._args):
            if is_nd and not is_const:
                new = next(ri)
                if isinstance(val, NDArray):
                    val._set_data(new)  # in-place CUDA semantics
                    outs.append(val)
                else:
                    outs.append(NDArray(new, ctx=ctx))
        return outs

    # ------------------------------------------------------------------
    def _build(self, grid, block, jax_vals):
        import jax
        from jax.experimental import pallas as pl

        from .ops.pallas_kernels import _use_interpret

        specs = []
        out_specs, out_shapes, aliases = [], [], {}
        n_out = 0
        for i, (val, (aname, dtype, is_nd, is_const)) in enumerate(
                zip(jax_vals, self._args)):
            if is_nd:
                spec = self._block_spec(pl, val.shape, grid, block, aname)
            else:
                # scalars ride as (1,)-shaped blocks, whole-array
                spec = pl.BlockSpec((1,), lambda *_: (0,) )
            specs.append(spec)
            if is_nd and not is_const:
                aliases[i] = n_out
                out_specs.append(spec)
                out_shapes.append(
                    jax.ShapeDtypeStruct(val.shape, val.dtype))
                n_out += 1
        if n_out == 0:
            raise MXNetError(
                "kernel '%s' has no output (a non-const pointer arg); "
                "CUDA kernels write through global pointers — declare at "
                "least one non-const pointer" % self._name)

        n_in = len(self._args)
        user_fn = self._fn
        arg_meta = list(self._args)

        def wrapper(*refs):
            ins, outs_r = refs[:n_in], refs[n_in:]
            mapped, oi = [], 0
            for j, (_n, _d, is_nd_j, is_const_j) in enumerate(arg_meta):
                if is_nd_j and not is_const_j:
                    # aliased buffer: the out ref IS the in-place array
                    mapped.append(outs_r[oi])
                    oi += 1
                else:
                    mapped.append(ins[j])
            user_fn(*mapped)

        call = pl.pallas_call(
            wrapper,
            grid=grid,
            in_specs=specs,
            out_specs=out_specs if len(out_specs) > 1 else out_specs[0],
            out_shape=out_shapes if len(out_shapes) > 1 else out_shapes[0],
            input_output_aliases=aliases,
            interpret=_use_interpret(),
        )

        def run(*vals):
            res = call(*vals)
            return res if isinstance(res, (list, tuple)) else [res]

        return run

    @staticmethod
    def _block_spec(pl, shape, grid, block, aname):
        if block is None:
            return pl.BlockSpec(shape, lambda *ids: (0,) * len(shape))
        if len(block) > len(shape):
            raise MXNetError(
                "block_dims %s has higher rank than arg '%s' shape %s"
                % (block, aname, shape))
        blk = tuple(block) + tuple(shape[len(block):])
        ngrid = len(grid)

        def index_map(*ids):
            # grid ids advance the blocked leading dims; trailing dims full
            ids = ids[:len(blk)]
            return tuple(ids) + (0,) * (len(blk) - len(ids))

        if ngrid > len(blk):
            raise MXNetError(
                "grid_dims %s has higher rank than block shape %s for arg "
                "'%s'" % (grid, blk, aname))
        return pl.BlockSpec(blk, index_map)


# API-parity alias: code written against the reference's mx.rtc.CudaModule
# gets the Pallas implementation (source must be Pallas, not CUDA — there
# is no CUDA toolchain on a TPU host; the class exists so the module
# surface matches python/mxnet/rtc.py).
CudaModule = PallasModule
