"""2-bit gradient compression with error feedback.

TPU-native equivalent of the reference's GradientCompression
(src/kvstore/gradient_compression.h:52: threshold quantize :111-134 with a
residual kept per key, .cc/.cu kernels; Python config kvstore.py
set_gradient_compression; docs/faq/gradient_compression.md).

Scheme (same as reference '2bit' type): each gradient element maps to one of
{-threshold, 0, +threshold} — values >= threshold send +threshold, values
<= -threshold send -threshold, the rest send 0. What was not sent stays in a
per-key residual that is added to the next gradient (error feedback), so the
compression is unbiased over time. On TPU the quantize/dequantize lower to
elementwise XLA select ops; the 16x wire-size reduction applies when grads
cross DCN (multi-host), which is where the reference used it too.
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["GradientCompression"]


class GradientCompression:
    """reference: gradient_compression.h:52 / kvstore set_gradient_compression."""

    def __init__(self, type="2bit", threshold=0.5):
        if type != "2bit":
            raise MXNetError("only '2bit' compression is supported "
                             "(matches reference kvstore types)")
        if threshold <= 0:
            raise MXNetError("threshold must be > 0")
        self.type = type
        self.threshold = float(threshold)
        self._residual = {}  # key -> jax array

    def get_params(self):
        return {"type": self.type, "threshold": self.threshold}

    def reset(self, key=None):
        """Drop error-feedback residuals for `key` (all devices), or all
        residuals when key is None — called when a kvstore key is
        (re)initialized."""
        if key is None:
            self._residual.clear()
            return
        for rk in [rk for rk in self._residual
                   if rk == key or (isinstance(rk, tuple) and rk
                                    and rk[0] == key)]:
            del self._residual[rk]

    def quantize(self, key, grad):
        """grad (NDArray) -> ternary compressed NDArray {-t, 0, +t}; the
        unsent remainder accumulates in the residual for `key`
        (reference: Quantize2BitKernelAll gradient_compression.cc)."""
        import jax.numpy as jnp

        from .ndarray.ndarray import NDArray

        g = grad._data
        res = self._residual.get(key)
        if res is not None:
            g = g + res
        t = self.threshold
        q = jnp.where(g >= t, t, jnp.where(g <= -t, -t, 0.0)).astype(g.dtype)
        self._residual[key] = g - q
        return NDArray(q, ctx=grad.context)

    def dequantize(self, compressed):
        """Identity on this in-memory representation (the reference's wire
        format packs 2-bit codes; the value decode yields the same ternary
        array this returns)."""
        return compressed
