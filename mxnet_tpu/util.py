"""General utilities (reference: python/mxnet/util.py)."""
from __future__ import annotations

import os

__all__ = ["makedirs", "get_gpu_count", "get_gpu_memory"]


def makedirs(d):
    """reference: util.py makedirs (py2 shim upstream; exist_ok here)."""
    os.makedirs(d, exist_ok=True)


def get_gpu_count():
    """Number of accelerator devices visible (reference: util.py
    get_gpu_count -> MXGetGPUCount; 'gpu' means 'accelerator' here)."""
    from .context import num_gpus

    return num_gpus()


def get_gpu_memory(dev_id=0):
    """(free, total) bytes on the accelerator (reference: util.py
    get_gpu_memory -> MXGetGPUMemoryInformation64)."""
    from .context import gpu_memory_info

    return gpu_memory_info(dev_id)
