"""Evaluation metrics (reference: python/mxnet/metric.py, 1.8k LoC).

Same registry and update(labels, preds) protocol; metric math runs on host
numpy (labels/preds are synced once per batch — the same boundary the
reference crosses for its metric updates)."""
from __future__ import annotations

import math

import numpy as _np

from .base import _Registry, MXNetError
from .ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "MCC", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "PCC", "Loss",
           "Torch", "Caffe", "CustomMetric", "np", "create", "register"]

_REG = _Registry("metric")


def register(klass):
    _REG.register(klass, klass.__name__)
    return klass


def create(metric, *args, **kwargs):
    """reference: metric.py create"""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    return _REG.create(metric, *args, **kwargs)


def _as_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _np.asarray(x)


class EvalMetric:
    """Base metric (reference: metric.py:68)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: %s" % dict(self.get_name_value())

    def get_config(self):
        config = self._kwargs.copy()
        config.update({"metric": self.__class__.__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


@register
class CompositeEvalMetric(EvalMetric):
    """reference: metric.py:278"""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(i) for i in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        try:
            for metric in self.metrics:
                metric.reset()
        except AttributeError:
            pass

    def get(self):
        names = []
        values = []
        for metric in self.metrics:
            name, value = metric.get()
            names.append(name) if not isinstance(name, list) else names.extend(name)
            values.append(value) if not isinstance(value, list) else values.extend(value)
        return (names, values)


def _check_label_shapes(labels, preds):
    if len(labels) != len(preds):
        raise MXNetError("labels and preds count mismatch: %d vs %d"
                         % (len(labels), len(preds)))


@register
class Accuracy(EvalMetric):
    """reference: metric.py:440"""

    def __init__(self, axis=1, name="accuracy", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def update(self, labels, preds):
        _check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(_np.int32).flat
            label = label.astype(_np.int32).flat
            self.sum_metric += (_np.asarray(pred) == _np.asarray(label)).sum()
            self.num_inst += len(_np.asarray(label))


@register
class TopKAccuracy(EvalMetric):
    """reference: metric.py:513"""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        assert top_k > 1, "use Accuracy for top_k=1"
        self.name += "_%d" % top_k

    def update(self, labels, preds):
        _check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype(_np.int32)
            pred = _np.argsort(_as_numpy(pred).astype(_np.float32), axis=-1)
            num_samples = pred.shape[0]
            num_classes = pred.shape[1]
            top = pred[:, num_classes - self.top_k:]
            for j in range(self.top_k):
                self.sum_metric += (top[:, j].flat == label.flat).sum()
            self.num_inst += num_samples


@register
class F1(EvalMetric):
    """Binary F1 (reference: metric.py:751)."""

    def __init__(self, name="f1", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self.average = average
        self.reset()

    def reset(self):
        self._tp = self._fp = self._fn = 0.0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype(_np.int32).ravel()
            pred = _as_numpy(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.astype(_np.int32).ravel()
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            prec = self._tp / max(self._tp + self._fp, 1e-12)
            rec = self._tp / max(self._tp + self._fn, 1e-12)
            f1 = 2 * prec * rec / max(prec + rec, 1e-12)
            self.sum_metric = f1
            self.num_inst = 1


@register
class MCC(EvalMetric):
    """Matthews correlation (reference: metric.py:845)."""

    def __init__(self, name="mcc", output_names=None, label_names=None,
                 average="macro"):
        super().__init__(name, output_names, label_names)
        self.reset()

    def reset(self):
        self._tp = self._fp = self._fn = self._tn = 0.0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype(_np.int32).ravel()
            pred = _as_numpy(pred)
            if pred.ndim > 1:
                pred = pred.argmax(axis=-1)
            pred = pred.astype(_np.int32).ravel()
            self._tp += ((pred == 1) & (label == 1)).sum()
            self._fp += ((pred == 1) & (label == 0)).sum()
            self._fn += ((pred == 0) & (label == 1)).sum()
            self._tn += ((pred == 0) & (label == 0)).sum()
            denom = math.sqrt((self._tp + self._fp) * (self._tp + self._fn) *
                              (self._tn + self._fp) * (self._tn + self._fn))
            mcc = ((self._tp * self._tn - self._fp * self._fn) / denom) if denom else 0.0
            self.sum_metric = mcc
            self.num_inst = 1


@register
class Perplexity(EvalMetric):
    """reference: metric.py:960"""

    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names, ignore_label=ignore_label)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype(_np.int32).reshape(-1)
            pred = _as_numpy(pred).astype(_np.float64)
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[_np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _np.where(ignore, 1.0, probs)
                num -= ignore.sum()
            loss -= _np.sum(_np.log(_np.maximum(1e-10, probs)))
            num += label.shape[0]
        self.sum_metric += loss
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += _np.abs(label - pred).mean()
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if len(label.shape) == 1:
                label = label.reshape(label.shape[0], 1)
            if len(pred.shape) == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += ((label - pred) ** 2.0).mean()
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    """reference: metric.py:1278"""

    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel().astype(_np.int64)
            pred = _as_numpy(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[_np.arange(label.shape[0]), label]
            self.sum_metric += (-_np.log(prob + self.eps)).sum()
            self.num_inst += label.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps, name, output_names, label_names)


@register
class PearsonCorrelation(EvalMetric):
    """reference: metric.py:1422"""

    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred).ravel()
            self.sum_metric += _np.corrcoef(pred, label)[0, 1]
            self.num_inst += 1


@register
class PCC(MCC):
    def __init__(self, name="pcc", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Loss(EvalMetric):
    """Mean of a loss output (reference: metric.py:1610)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        if isinstance(preds, NDArray):
            preds = [preds]
        for pred in preds:
            loss = _as_numpy(pred).sum()
            self.sum_metric += loss
            self.num_inst += pred.size


@register
class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


class CustomMetric(EvalMetric):
    """reference: metric.py CustomMetric"""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__ if feval.__name__ != "<lambda>" else "custom()"
        super().__init__("custom(%s)" % name if "(" not in name else name,
                         output_names, label_names)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            _check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                (sum_metric, num_inst) = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Create a CustomMetric from a numpy function (reference: metric.py np)."""

    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)


_REG.register(Accuracy, "acc")
_REG.register(TopKAccuracy, "top_k_acc")
_REG.register(CrossEntropy, "ce")
_REG.register(NegativeLogLikelihood, "nll_loss")
