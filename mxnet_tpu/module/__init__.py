"""mxnet_tpu.module — symbolic training loop (reference: python/mxnet/module).

Module binds a Symbol to contexts; multi-context = mesh sharding (GSPMD)
instead of per-context executor copies. See module.py docstring.
"""
from .base_module import BaseModule, BatchEndParam
from .module import Module
from .bucketing_module import BucketingModule
from .sequential_module import SequentialModule
from .python_module import PythonModule, PythonLossModule

__all__ = ["BaseModule", "BatchEndParam", "Module", "BucketingModule",
           "PythonModule", "PythonLossModule",
           "SequentialModule"]
