"""SequentialModule — chain modules, feeding outputs to the next.

Reference: python/mxnet/module/sequential_module.py.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from ..io import DataBatch, DataDesc
from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None

    def add(self, module, **kwargs):
        self._modules.append(module)
        self._metas.append(kwargs)
        return self

    @property
    def data_shapes(self):
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes or []

    @property
    def output_shapes(self):
        return self._modules[-1].output_shapes

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        assert shared_module is None
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._label_shapes = label_shapes

        cur_shapes = data_shapes
        for i, (module, meta) in enumerate(zip(self._modules, self._metas)):
            take_labels = meta.get(self.META_TAKE_LABELS, False)
            mod_labels = label_shapes if take_labels else None
            need_grad = inputs_need_grad if i == 0 else True
            if meta.get(self.META_AUTO_WIRING, False) and i > 0:
                prev = self._modules[i - 1].output_shapes
                dnames = module.data_names
                cur_shapes = [(dnames[j], s) for j, (_, s) in enumerate(prev)]
            module.bind(cur_shapes, mod_labels, for_training,
                        inputs_need_grad=need_grad,
                        force_rebind=force_rebind, grad_req=grad_req)
            cur_shapes = module.output_shapes
        self.binded = True

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        for module in self._modules:
            module.init_params(initializer, arg_params, aux_params,
                               allow_missing=True, force_init=force_init,
                               allow_extra=True)
        self.params_initialized = True

    def get_params(self):
        arg_params, aux_params = {}, {}
        for module in self._modules:
            a, x = module.get_params()
            arg_params.update(a)
            aux_params.update(x)
        return arg_params, aux_params

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        for module in self._modules:
            module.init_optimizer(kvstore, optimizer, optimizer_params,
                                  force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        batch = data_batch
        for i, (module, meta) in enumerate(zip(self._modules, self._metas)):
            module.forward(batch, is_train=is_train)
            if i == len(self._modules) - 1:
                break
            outs = module.get_outputs()
            label = data_batch.label \
                if self._metas[i + 1].get(self.META_TAKE_LABELS, False) else []
            batch = DataBatch(
                data=outs, label=label,
                provide_data=[DataDesc("data%d" % j, tuple(o.shape))
                              for j, o in enumerate(outs)])

    def backward(self, out_grads=None):
        for i, module in reversed(list(enumerate(self._modules))):
            module.backward(out_grads)
            if i == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        for module, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS, False):
                module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for module in self._modules:
            module.install_monitor(mon)
