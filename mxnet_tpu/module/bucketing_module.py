"""BucketingModule — variable-length (e.g. seq-len) training via per-bucket
executors.

Reference: python/mxnet/module/bucketing_module.py + the shared-memory
co-binding machinery (graph_executor shared pool :654, docs/faq/bucketing.md).

TPU-native: each bucket is a Module whose Executor jit-compiles per shape —
exactly the XLA executable-cache model (SURVEY §5.7: bucketing is how the
reference handled long sequences; here it is nearly free). Parameters are
shared across buckets by pointing every bucket's executor at the SAME
NDArray objects — no copy, no memory-pool gymnastics.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._opt_config = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        return self._curr_module.symbol

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        return self._curr_module.output_shapes

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                    force_rebind=False, shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """reference: bucketing_module.py switch_bucket."""
        assert self.binded
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self.for_training,
                        self.inputs_need_grad, force_rebind=False,
                        shared_module=self._buckets[self._default_bucket_key],
                        grad_req=self._buckets[self._default_bucket_key]._grad_req)
            # share parameter STORAGE with the default bucket: same NDArray
            # objects, so updates through any bucket are visible to all
            default = self._buckets[self._default_bucket_key]._exec
            ex = module._exec
            for name in module._param_names:
                if name in default.arg_dict:
                    ex.arg_arrays[ex._arg_names.index(name)] = \
                        default.arg_dict[name]
                    gi = ex._arg_names.index(name)
                    di = default._arg_names.index(name)
                    if default.grad_arrays[di] is not None:
                        ex.grad_arrays[gi] = default.grad_arrays[di]
            for name in module._aux_names:
                if name in default.aux_dict:
                    ex.aux_arrays[ex._aux_names.index(name)] = \
                        default.aux_dict[name]
            module.params_initialized = self.params_initialized
            if self._opt_config is not None:
                module._optimizer = self._buckets[
                    self._default_bucket_key]._optimizer
                module._updater = self._buckets[
                    self._default_bucket_key]._updater
                module.optimizer_initialized = True
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        assert self.binded
        self._buckets[self._default_bucket_key].init_params(
            initializer, arg_params, aux_params, allow_missing, force_init,
            allow_extra)
        self.params_initialized = True
        for mod in self._buckets.values():
            mod.params_initialized = True

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._buckets[self._default_bucket_key].init_optimizer(
            kvstore, optimizer, optimizer_params, force_init)
        self._opt_config = (kvstore, optimizer, optimizer_params)
        for key, mod in self._buckets.items():
            if key != self._default_bucket_key:
                mod._optimizer = self._buckets[self._default_bucket_key]._optimizer
                mod._updater = self._buckets[self._default_bucket_key]._updater
                mod.optimizer_initialized = True
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        bucket_key = getattr(data_batch, "bucket_key", self._default_bucket_key)
        self.switch_bucket(bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)
