"""BaseModule — the symbolic training-loop interface.

Reference: python/mxnet/module/base_module.py (BaseModule :?, fit :409 —
epoch loop of forward_backward :193 / update / metrics / checkpoints).
The TPU build keeps the exact interface; the compute underneath is the
jit-compiled Executor (executor.py) instead of GraphExecutor.
"""
from __future__ import annotations

import logging
import time

import numpy as _np

from ..base import MXNetError, unpad_outputs
from .. import env as _env
from .. import metric as metric_mod
from .. import io as io_mod
from .. import ndarray as nd


def _as_metric(m):
    if isinstance(m, metric_mod.EvalMetric):
        return m
    return metric_mod.create(m)


def _parse_data(data, data_names, label_names):
    if isinstance(data, io_mod.DataIter):
        return data
    raise MXNetError("expected a DataIter, got %r" % (type(data),))


class BaseModule(object):
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.inputs_need_grad = False
        self._symbol = None

    # -- abstract interface (Module implements) ----------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def bind(self, *args, **kwargs):
        raise NotImplementedError()

    def init_params(self, *args, **kwargs):
        raise NotImplementedError()

    # -- composite ops -----------------------------------------------------
    def forward_backward(self, data_batch):
        """reference: base_module.py:193."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def supports_fused_step(self):
        """Whether fit() may replace forward_backward()+update() with one
        fused compiled step (Module overrides; everything else stays on
        the op-by-op composite path)."""
        return False

    def fused_step(self, data_batch):
        raise NotImplementedError()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """reference: base_module.py score."""
        assert self.binded and self.params_initialized
        eval_metric = _as_metric(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                for cb in _as_list(batch_end_callback):
                    cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                     eval_metric=eval_metric, locals=locals()))
        if score_end_callback is not None:
            for cb in _as_list(score_end_callback):
                cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                 eval_metric=eval_metric, locals=locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        """reference: base_module.py predict."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = getattr(eval_batch, "pad", 0) or 0
            outs = unpad_outputs(self.get_outputs(), pad, copy=True)
            output_list.append(outs)
        if not output_list:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for o in output_list:
                if len(o) != num_outputs:
                    raise MXNetError("cannot merge batches with different "
                                     "numbers of outputs")
            merged = [nd.concatenate([o[i] for o in output_list])
                      for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = getattr(eval_batch, "pad", 0) or 0
            outs = unpad_outputs(self.get_outputs(), pad)
            yield outs, nbatch, eval_batch

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None,
            checkpoint_dir=None, checkpoint_period=1, resume=None):
        """The canonical symbolic training loop (reference:
        base_module.py:409; call stack SURVEY §3.1).

        Fault tolerance (beyond the reference — docs/fault_tolerance.md):
        `checkpoint_dir` enables crash-consistent end-of-epoch checkpoints
        (params + optimizer states, atomic rename, keep-last-N) every
        `checkpoint_period` epochs via parallel.resilience.CheckpointManager;
        `resume='auto'` restores the newest COMPLETE checkpoint from that
        directory — params, optimizer states, RNG chain and epoch cursor —
        so a restarted generation (tools/launch.py --max-restarts) continues
        training instead of starting from epoch 0. `resume=<int>` pins an
        epoch explicitly (raises MXNetError if that step is corrupt)."""
        assert num_epoch is not None, "please specify number of epochs"
        from ..initializer import Uniform

        initializer = initializer or Uniform(0.01)

        mgr = None
        if checkpoint_dir is not None:
            from ..parallel.resilience import CheckpointManager

            mgr = CheckpointManager(checkpoint_dir)
        elif resume is not None:
            raise MXNetError("fit(resume=...) needs checkpoint_dir=")

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        resume_skip = 0
        data_restored = False
        if mgr is not None and resume is not None:
            header = mgr.restore(
                load_params=self.load_params,
                load_states=self.load_optimizer_states,
                step=None if resume == "auto" else int(resume))
            # restore() returns None only for resume='auto' with no complete
            # checkpoint (fresh start); an explicit epoch that is missing or
            # corrupt raises its own MXNetError inside restore()
            if header is not None:
                begin_epoch = int(header["meta"].get(
                    "epoch", header["step"])) + 1
                # a preemption checkpoint lands MID-epoch: its weights
                # already include the first `batches_done` updates of the
                # interrupted epoch, so the resumed epoch fast-forwards
                # the iterator past them instead of re-applying them
                resume_skip = int(header["meta"].get("batches_done", 0))
                # a checkpointable iterator (mxnet_tpu.data StreamDataIter
                # and friends) restores its exact mid-epoch cursor instead
                # of blind fast-forwarding: set_state() arms a one-shot
                # reset skip so the epoch-top reset below keeps it
                data_state = header["meta"].get("data_state")
                if data_state is not None and \
                        hasattr(train_data, "set_state"):
                    train_data.set_state(data_state)
                    data_restored = True
                self.logger.info(
                    "resumed from checkpoint step %d (%s); continuing at "
                    "epoch %d%s%s", header["step"], mgr.directory,
                    begin_epoch,
                    " batch %d" % resume_skip if resume_skip else "",
                    " (exact data cursor)" if data_restored else "")
        if validation_metric is None:
            validation_metric = eval_metric
        eval_metric = _as_metric(eval_metric)

        from ..parallel import resilience
        from ..parallel.resilience import maybe_inject_fault
        from .. import telemetry

        # Graceful preemption (docs/fault_tolerance.md): once checkpoints
        # are configured, SIGTERM stops killing the process mid-step —
        # the handler just raises a flag, the in-flight step finishes,
        # and the step-boundary check below lands an emergency checkpoint
        # inside MXTPU_PREEMPT_GRACE_S before exiting with the
        # preemption rc (a free restart under tools/launch.py).
        if mgr is not None:
            resilience.install_preemption_handler()

        # input-pipeline starvation metrics: seconds spent WAITING on the
        # data iterator vs. seconds spent in forward/backward/update — the
        # first thing to read when a run is slow (is it the loader or the
        # chip?)
        tm_wait = telemetry.counter("mxtpu_data_wait_seconds_total",
                                    {"src": "fit"})
        tm_compute = telemetry.counter("mxtpu_data_compute_seconds_total",
                                       {"src": "fit"})

        # MXTPU_SHARDED_STEP: run forward+backward+update as ONE compiled
        # donated executable per step (module doc: docs/sharded_training.md).
        # A monitor needs per-op intermediate outputs, so it forces the
        # op-by-op composite path.
        use_fused = (monitor is None and _env.get("MXTPU_SHARDED_STEP")
                     and self.supports_fused_step())

        # MXTPU_DATA_PREFETCH: overlap batch N+1's host decode + async
        # host->device copy with batch N's compute (docs/data_pipeline.md).
        # The fused path places with the trainer's mesh so step_batch
        # consumes already-sharded arrays (executor._place_inputs no-ops).
        use_prefetch = _env.get("MXTPU_DATA_PREFETCH")

        fit_updates = 0
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            batch_iter = iter(train_data)
            if epoch == begin_epoch and resume_skip:
                if data_restored:
                    # the restored cursor already sits past these batches;
                    # only the batch numbering needs to catch up
                    nbatch = resume_skip
                else:
                    for _ in range(resume_skip):
                        try:
                            next(batch_iter)
                        except StopIteration:
                            break
                        nbatch += 1
            prefetcher = None
            if use_prefetch:
                from ..data import DevicePrefetcher

                batch_iter = prefetcher = DevicePrefetcher(
                    batch_iter, mesh=getattr(self, "_mesh", None),
                    src="fit")
            while True:
                t_wait = time.perf_counter()
                try:
                    data_batch = next(batch_iter)
                except StopIteration:
                    break
                t_step = time.perf_counter()
                tm_wait.inc(t_step - t_wait)
                # goodput bracket opens back-dated to t_wait (the iterator
                # wait belongs to the step) but only after a successful
                # next() — StopIteration must not leave a dangling bracket
                telemetry.goodput.step_start(kind="fit", t0=t_wait)
                telemetry.goodput.add("data_wait", t_step - t_wait)
                if monitor is not None:
                    monitor.tic()
                # distributed tracing: one root span per fit step; the
                # data wait predates the root, so it is emitted
                # retroactively as a child with measured times
                with telemetry.tracing.root(
                        "train.step", component="train",
                        attrs={"step": fit_updates + 1,
                               "kind": "fit"}) as t_span:
                    telemetry.tracing.emit_span(
                        "train.data_wait",
                        time.time() - (t_step - t_wait), t_step - t_wait,
                        t_span, component="train")
                    telemetry.goodput.mark_launch()
                    if use_fused:
                        with telemetry.tracing.span("train.fused_step"), \
                                telemetry.goodput.phase("compute"):
                            self.fused_step(data_batch)
                    else:
                        with telemetry.tracing.span("train.fwd_bwd"), \
                                telemetry.goodput.phase("compute"):
                            self.forward_backward(data_batch)
                        with telemetry.tracing.span("train.optimizer"), \
                                telemetry.goodput.phase("compute"):
                            self.update()
                    fit_updates += 1
                    examples = None
                    try:
                        examples = int(data_batch.data[0].shape[0])
                    except (AttributeError, IndexError, TypeError):
                        pass
                    telemetry.observe_step(time.perf_counter() - t_step,
                                           examples=examples,
                                           step=fit_updates, kind="fit")
                    telemetry.goodput.step_end(step=fit_updates)
                # step-boundary fault hook: counts updates since THIS
                # process started (no-op unless MXTPU_FAULT_INJECT is set)
                maybe_inject_fault(fit_updates)
                if mgr is not None and resilience.preemption_requested():
                    if prefetcher is not None:
                        # freeze the pipeline first: producer threads are
                        # joined and the delivered-batch cursor is final
                        # before it lands in the checkpoint meta
                        prefetcher.close()

                    def _emergency_save(_epoch=epoch, _done=nbatch + 1,
                                        _cursor=prefetcher or train_data):
                        arg_p, aux_p = self.get_params()
                        self.set_params(arg_p, aux_p)  # sync exec copies
                        # meta epoch = _epoch - 1 + batches_done: resume
                        # re-enters the interrupted epoch but fast-forwards
                        # past the batches whose updates these weights
                        # already carry (exact resume-equivalence)
                        meta = {"epoch": _epoch - 1, "preempt": True,
                                "batches_done": _done}
                        if hasattr(_cursor, "state"):
                            try:
                                # exact mid-epoch cursor: resume restores
                                # it via set_state instead of blind
                                # fast-forwarding (data/sharded_stream.py)
                                meta["data_state"] = _cursor.state()
                            except MXNetError:
                                pass  # inner iterator has no cursor
                        mgr.save(_epoch, save_params=self.save_params,
                                 save_states=self.save_optimizer_states,
                                 meta=meta)
                    resilience.maybe_preempt_exit(
                        emergency_save=_emergency_save)
                self.update_metric(eval_metric, data_batch.label)
                if monitor is not None:
                    monitor.toc_print()
                if batch_end_callback is not None:
                    for cb in _as_list(batch_end_callback):
                        cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                         eval_metric=eval_metric,
                                         locals=locals()))
                nbatch += 1
                tm_compute.inc(time.perf_counter() - t_step)

            if prefetcher is not None:
                prefetcher.close()  # join the producer between epochs
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)

            arg_p, aux_p = self.get_params()
            self.set_params(arg_p, aux_p)  # sync exec copies

            if mgr is not None and (epoch + 1) % checkpoint_period == 0:
                mgr.save(epoch, save_params=self.save_params,
                         save_states=self.save_optimizer_states,
                         meta={"epoch": epoch})

            if epoch_end_callback is not None:
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

    # -- misc --------------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    def get_params(self):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def install_monitor(self, mon):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()


class BatchEndParam(object):
    """reference: callback BatchEndParam namedtuple."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def _as_list(obj):
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]
