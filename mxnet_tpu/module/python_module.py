"""Pure-Python modules pluggable into module pipelines (reference:
python/mxnet/module/python_module.py — PythonModule implements the module
API as mostly-empty hooks; PythonLossModule turns a score→gradient
function into a loss head for SequentialModule-style compositions)."""
from __future__ import annotations

import logging

import numpy as _np

from .base_module import BaseModule


class PythonModule(BaseModule):
    """Parameter-less module skeleton (reference: python_module.py:28).
    Subclasses implement `forward` and `_compute_output_shapes`."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = None if label_names is None else list(label_names)
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # -- naming / shapes ---------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # -- parameters (none by default) --------------------------------------
    def get_params(self):
        return ({}, {})

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        self.params_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        if self._label_shapes is None:
            return
        if pre_sliced:
            raise RuntimeError("PythonModule does not support presliced "
                               "labels")
        eval_metric.update(labels, self.get_outputs())

    # -- setup -------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        assert grad_req == "write", "Python module only support write " \
                                    "gradient"
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        names = [d[0] if isinstance(d, (tuple, list)) else d.name
                 for d in data_shapes]
        assert names == self._data_names, (names, self._data_names)
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        if label_shapes is not None:
            assert self._label_names is not None
            lnames = [d[0] if isinstance(d, (tuple, list)) else d.name
                      for d in label_shapes]
            assert lnames == self._label_names
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True


class PythonLossModule(PythonModule):
    """Scores-in/gradient-out loss head (reference: python_module.py:245).
    `grad_func(scores, labels) -> grad` supplies the backward; without it,
    subclass `_backward_impl`."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names, [name + "_output"],
                         logger=logger)
        self._name = name
        assert len(data_names) == 1
        assert len(label_names) == 1
        self._scores = None
        self._labels = None
        self._scores_grad = None
        if grad_func is not None:
            assert callable(grad_func)
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        shape = self._data_shapes[0][1] \
            if isinstance(self._data_shapes[0], (tuple, list)) \
            else self._data_shapes[0].shape
        return [(self._name + "_output", shape)]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        assert merge_multi_context is True
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, "For a loss module, out_grads should " \
                                  "be None"
        assert self.for_training
        self._backward_impl()

    def _backward_impl(self):
        if self._grad_func is not None:
            from .. import ndarray as nd

            grad = self._grad_func(self._scores, self._labels)
            if not isinstance(grad, nd.NDArray):
                grad = nd.array(_np.asarray(grad))
            self._scores_grad = grad
        else:
            raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        assert merge_multi_context is True
        return [self._scores_grad]

    def install_monitor(self, mon):
        raise NotImplementedError()
