"""Module — bind a Symbol to contexts and train it.

Reference: python/mxnet/module/module.py (Module :40 — bind :364,
init_params :259, init_optimizer :474, forward :575, backward :629,
update :646) + executor_group.py DataParallelExecutorGroup :143.

TPU-native mapping: ONE Executor regardless of context count. A multi-
context list becomes a 1-D `dp` mesh over those devices and the executor's
data arguments are sharded on the batch dimension (GSPMD replaces the
reference's per-context executor copies + manual batch slicing + kvstore
gradient reduce: the gradients arrive already summed because the graph is
compiled globally over the mesh).
"""
from __future__ import annotations

import logging

import numpy as _np

from ..base import MXNetError
from .. import context as ctx_mod
from .. import ndarray as nd
from .. import optimizer as opt_mod
from ..executor import Executor
from ..initializer import InitDesc
from ..io import DataDesc
from ..ndarray import NDArray
from .base_module import BaseModule

__all__ = ["Module"]


def _normalize_shapes(shapes, default_names):
    """Accept [('name', shape)] / [DataDesc] / [shape]."""
    out = []
    for i, s in enumerate(shapes or []):
        if isinstance(s, DataDesc):
            out.append((s.name, tuple(s.shape)))
        elif isinstance(s, (list, tuple)) and len(s) == 2 and isinstance(s[0], str):
            out.append((s[0], tuple(s[1])))
        else:
            name = default_names[i] if i < len(default_names) else "data%d" % i
            out.append((name, tuple(s)))
    return out


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        context = context if context is not None else ctx_mod.current_context()
        if isinstance(context, ctx_mod.Context):
            context = [context]
        self._context = list(context)
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = set(fixed_param_names or [])

        arg_names = symbol.list_arguments()
        input_names = set(self._data_names + self._label_names)
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()

        self._exec = None
        self._updater = None
        self._optimizer = None
        self._kvstore = None
        self._mesh = None
        self._data_shapes = None
        self._label_shapes = None
        self._grad_req = "write"
        self._fused = None  # ModuleFusedStep when MXTPU_SHARDED_STEP armed

    # -- properties --------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return [DataDesc(n, s) for n, s in self._data_shapes or []]

    @property
    def label_shapes(self):
        return [DataDesc(n, s) for n, s in self._label_shapes or []]

    @property
    def output_shapes(self):
        assert self.binded
        return list(zip(self._output_names,
                        [tuple(o.shape) for o in self._exec.outputs]))

    # -- bind --------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """reference: module.py:364. Allocates args via simple_bind; multi-
        context => dp mesh sharding (see module docstring)."""
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req

        data_shapes = _normalize_shapes(data_shapes, self._data_names)
        label_shapes = _normalize_shapes(label_shapes, self._label_names) \
            if label_shapes else []
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes

        shape_kwargs = dict(data_shapes + label_shapes)
        # drop label args absent from the graph (predict-time binding)
        shape_kwargs = {k: v for k, v in shape_kwargs.items()
                        if k in self._symbol.list_arguments()}

        req = {}
        for n in self._symbol.list_arguments():
            if n in self._fixed_param_names:
                req[n] = "null"
            elif n in dict(data_shapes):
                req[n] = grad_req if (for_training and inputs_need_grad) else "null"
            elif n in dict(label_shapes):
                req[n] = "null"
            else:
                req[n] = grad_req if for_training else "null"

        mesh = None
        if len(self._context) > 1:
            from ..parallel.mesh import make_mesh

            mesh = make_mesh([("dp", len(self._context))],
                             devices=[c.jax_device() for c in self._context])
        self._mesh = mesh

        ex = self._symbol.simple_bind(ctx=self._context[0], grad_req=req,
                                      **shape_kwargs)
        ex._mesh = mesh
        ex._data_arg_names = set(dict(data_shapes + label_shapes))
        if shared_module is not None and shared_module._exec is not None:
            ex.copy_params_from(shared_module._exec.arg_dict,
                                shared_module._exec.aux_dict,
                                allow_extra_params=True)
        self._exec = ex
        self.binded = True

    # -- params ------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        """reference: module.py:259."""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        # Module.load stashes checkpoint params; use them unless overridden
        if arg_params is None:
            arg_params = getattr(self, "_arg_params_cache", None)
        if aux_params is None:
            aux_params = getattr(self, "_aux_params_cache", None)
        from ..initializer import Uniform

        attr_dict = self._symbol.attr_dict()
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                src = arg_params[name]
                arr._set_data(src._data if isinstance(src, NDArray)
                              else nd.array(src)._data)
            elif initializer is not None:
                desc = InitDesc(name, attrs=attr_dict.get(name, {}))
                initializer(desc, arr)
            elif not allow_missing:
                raise MXNetError("no initializer and no value for '%s'" % name)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                src = aux_params[name]
                arr._set_data(src._data if isinstance(src, NDArray)
                              else nd.array(src)._data)
            elif initializer is not None:
                desc = InitDesc(name, attrs=attr_dict.get(name, {}))
                initializer(desc, arr)
        self.params_initialized = True

    def get_params(self):
        """reference: module.py get_params — host copies of params."""
        assert self.binded and self.params_initialized
        arg_params = {n: self._exec.arg_dict[n].copyto(ctx_mod.cpu())
                      for n in self._param_names}
        aux_params = {n: self._exec.aux_dict[n].copyto(ctx_mod.cpu())
                      for n in self._aux_names}
        return arg_params, aux_params

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """reference: module.py:474. The kvstore argument is accepted for
        API parity; gradient aggregation is compiled into the graph (mesh
        psum), so every kvstore type behaves like the synchronous 'device'
        kvstore (SURVEY §2.3 divergence: dist_async not reproduced)."""
        if self.optimizer_initialized and not force_init:
            return
        assert self.binded and self.params_initialized
        if isinstance(optimizer, opt_mod.Optimizer):
            opt = optimizer
        else:
            opt_params = dict(optimizer_params or {})
            opt_params.setdefault("param_idx2name",
                                  {i: n for i, n in enumerate(self._param_names)})
            # reference module.py:474: default rescale_grad = 1/batch_size
            # (loss-head ops emit sum-over-batch gradients)
            if self._data_shapes:
                batch_size = self._data_shapes[0][1][0]
                opt_params.setdefault("rescale_grad", 1.0 / batch_size)
            opt = opt_mod.create(optimizer, **opt_params)
        self._optimizer = opt
        self._updater = opt_mod.get_updater(opt)
        self._kvstore = kvstore
        self.optimizer_initialized = True

    # -- execution ---------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        """reference: module.py:575."""
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        data = data_batch.data if hasattr(data_batch, "data") else data_batch
        for name_shape, arr in zip(self._data_shapes, data):
            feeds[name_shape[0]] = arr
        labels = getattr(data_batch, "label", None) or []
        for name_shape, arr in zip(self._label_shapes, labels):
            if name_shape[0] in self._exec._arg_names:
                feeds[name_shape[0]] = arr
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        """reference: module.py:629."""
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        """Apply optimizer to each parameter using its gradient (reference:
        module.py:646; the kvstore push/pull pair collapses into the
        in-graph gradient sum)."""
        assert self.optimizer_initialized
        for i, name in enumerate(self._param_names):
            if self._exec.grad_req.get(name, "null") == "null":
                continue
            grad = self._exec.grad_dict[name]
            if grad is None:
                continue
            self._updater(i, grad, self._exec.arg_dict[name])

    # -- the fused whole-step path (MXTPU_SHARDED_STEP) ---------------------
    def supports_fused_step(self):
        """Whether fit() may run this module through ONE compiled
        forward+backward+update executable (parallel.sharded_trainer.
        ModuleFusedStep): bound for training with an optimizer, plain
        'write' grads, and no input-gradient consumers."""
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized and self.for_training):
            return False
        if self.inputs_need_grad:
            return False
        return any(self._exec.grad_req.get(n, "null") == "write"
                   for n in self._param_names)

    def fused_step(self, data_batch):
        """One fused train step (forward + backward + optimizer update as
        a single donated executable); outputs land in get_outputs() on
        device. fit() calls this instead of forward_backward()+update()
        when MXTPU_SHARDED_STEP is armed — no model-code changes."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        if self._fused is None:
            from ..parallel.sharded_trainer import ModuleFusedStep

            self._fused = ModuleFusedStep(self._exec, self._optimizer,
                                          self._param_names)
        feeds = {}
        data = data_batch.data if hasattr(data_batch, "data") else data_batch
        for name_shape, arr in zip(self._data_shapes, data):
            feeds[name_shape[0]] = arr
        labels = getattr(data_batch, "label", None) or []
        for name_shape, arr in zip(self._label_shapes, labels):
            if name_shape[0] in self._exec._arg_names:
                feeds[name_shape[0]] = arr
        return self._fused(feeds)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        return [self._exec.grad_dict[n] for n, _ in self._data_shapes]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    def install_monitor(self, mon):
        mon.install(self._exec)

    # -- checkpointing -----------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """reference: module.py save_checkpoint → model.py:394 format
        (prefix-symbol.json + prefix-%04d.params)."""
        from ..model import save_checkpoint

        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self.symbol, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint

        sym, arg_params, aux_params = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded_params = (arg_params, aux_params)
        mod._arg_params_cache = arg_params
        mod._aux_params_cache = aux_params
        return mod

    def load_params(self, fname):
        from ..model import load_params as _load

        arg_params, aux_params = _load(fname)
        self.set_params(arg_params, aux_params)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def save_optimizer_states(self, fname):
        from ..base import atomic_writer

        assert self.optimizer_initialized
        if self._fused is not None:
            # fused steps keep optimizer state device-side; write it back
            # into the op-by-op updater so the states file stays portable
            self._fused.sync_updater(self._updater)
        # atomic (temp + fsync + rename): save_checkpoint's .states file
        # gets the same crash-consistency as its .params file
        with atomic_writer(fname, "wb") as f:
            f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def reshape(self, data_shapes, label_shapes=None):
        """reference: module.py reshape — on TPU just a re-bind; executable
        cache keyed on shape does the heavy lifting."""
        self.bind(data_shapes, label_shapes, for_training=self.for_training,
                  inputs_need_grad=self.inputs_need_grad, force_rebind=True,
                  grad_req=self._grad_req)
