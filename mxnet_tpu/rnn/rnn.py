"""RNN checkpoint helpers (reference: python/mxnet/rnn/rnn.py): save/load
model checkpoints with cell weights unpacked into readable per-gate
entries, and the fit() callback wiring them in."""
from __future__ import annotations

from ..model import load_checkpoint, save_checkpoint


def _as_cells(cells):
    return cells if isinstance(cells, (list, tuple)) else [cells]


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params,
                        aux_params):
    """save_checkpoint with fused weights unpacked (reference: rnn.py:32)."""
    args = arg_params
    for cell in _as_cells(cells):
        args = cell.unpack_weights(args)
    save_checkpoint(prefix, epoch, symbol, args, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """load_checkpoint + pack_weights (reference: rnn.py:62)."""
    sym, args, auxs = load_checkpoint(prefix, epoch)
    for cell in _as_cells(cells):
        args = cell.pack_weights(args)
    return sym, args, auxs


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback checkpointing with unpacked weights (reference:
    rnn.py:97; analogue of mx.callback.do_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback
