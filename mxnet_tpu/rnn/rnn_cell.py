"""Symbolic RNN cells (reference: python/mxnet/rnn/rnn_cell.py).

Derived from the reference implementation (Apache-2.0); cell/parameter
naming (i2h/h2h weight-bias layout, gate order) kept for checkpoint
compatibility with reference-trained models.

The cell API unrolls recurrences explicitly into the symbolic graph —
the formulation BucketingModule's per-length executors consume. Under
this framework each unrolled bucket length compiles to its own XLA
executable (shared weights), which is exactly the reference's bucketing
memory-sharing story (SURVEY §5.7) expressed through the jit cache.

Divergence note: `begin_state()`'s default initial state is a
`_rnn_state_zeros` node whose batch size rides the cell's first unroll
input (the reference writes literal shape (0, H) and lets nnvm fill the
batch; jax shape inference has no wildcard dims, so the zero state is
derived from the data symbol instead). Calling begin_state() before
unroll with the default func therefore requires the unroll path; passing
func=symbol.Variable (feed states as inputs) works as in the reference.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import symbol


def _cells_state_info(cells):
    return sum([c.state_info for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _cells_unpack_weights(cells, args):
    for cell in cells:
        args = cell.unpack_weights(args)
    return args


def _cells_pack_weights(cells, args):
    for cell in cells:
        args = cell.pack_weights(args)
    return args


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """Split a merged (N,T,C)/(T,N,C) symbol into per-step symbols, or
    merge a step list back — the reference's input/output plumbing."""
    assert inputs is not None
    axis = layout.find("T")
    if isinstance(inputs, symbol.Symbol):
        in_axis = (in_layout or layout).find("T")
        if merge is False:
            steps = list(symbol.SliceChannel(inputs, num_outputs=length,
                                             axis=in_axis,
                                             squeeze_axis=True))
            return steps, axis
        if in_axis != axis:
            perm = [0, 1, 2]
            perm[in_axis], perm[axis] = perm[axis], perm[in_axis]
            inputs = symbol.transpose(inputs, axes=tuple(perm))
        return inputs, axis
    # list of (N, C) step symbols: merged ONLY when merge is True —
    # merge=None (the default) keeps the per-step list, the reference's
    # `outputs[-1]` last-hidden idiom depends on it
    if merge is True:
        steps = [symbol.expand_dims(s, axis=axis) for s in inputs]
        return symbol.Concat(*steps, dim=axis), axis
    return list(inputs), axis


class RNNParams(object):
    """Container for cell weights (reference: rnn_cell.py:78) — shared
    between cells by passing the same instance."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.var(name, **kwargs)
        return self._params[name]


class BaseRNNCell(object):
    """Abstract symbolic RNN cell (reference: rnn_cell.py:108)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self._begin_ref = None   # data symbol the zero state derives from
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in getattr(self, "_cells", ()):
            cell.reset()

    def __call__(self, inputs, states):
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [e["shape"] for e in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, **kwargs):
        """Initial states. Default: zero states whose batch dimension is
        derived from the unroll input (see module docstring); pass
        func=symbol.var to feed states as graph inputs instead."""
        assert not self._modified, (
            "After applying modifier cells the base cell cannot be called "
            "directly. Call the modifier cell instead.")
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = "%sbegin_state_%d" % (self._prefix, self._init_counter)
            if func is not None and func is not symbol.zeros:
                if func in (symbol.var, symbol.Variable):
                    states.append(func(name))
                else:
                    states.append(func(name=name, **dict(kwargs, **info)))
                continue
            ref = kwargs.get("_ref", self._begin_ref)
            if ref is None:
                raise MXNetError(
                    "begin_state(): default zero states need the unroll "
                    "input to derive the batch dimension — call unroll(), "
                    "or pass func=symbol.var to feed states explicitly")
            tail = tuple(info["shape"][1:])
            states.append(symbol._rnn_state_zeros(ref, state_shape=tail,
                                                  name=name))
        return states

    def unpack_weights(self, args):
        """Split fused i2h/h2h matrices into per-gate entries
        (reference: rnn_cell.py:225)."""
        args = args.copy()
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group in ("i2h", "h2h"):
            w = args.pop("%s%s_weight" % (self._prefix, group))
            b = args.pop("%s%s_bias" % (self._prefix, group))
            for j, gate in enumerate(self._gate_names):
                args["%s%s%s_weight" % (self._prefix, group, gate)] = \
                    w[j * h:(j + 1) * h].copy()
                args["%s%s%s_bias" % (self._prefix, group, gate)] = \
                    b[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        """Inverse of unpack_weights (reference: rnn_cell.py:265)."""
        from .. import ndarray as nd

        args = args.copy()
        if not self._gate_names:
            return args
        for group in ("i2h", "h2h"):
            ws, bs = [], []
            for gate in self._gate_names:
                ws.append(args.pop("%s%s%s_weight"
                                   % (self._prefix, group, gate)))
                bs.append(args.pop("%s%s%s_bias"
                                   % (self._prefix, group, gate)))
            args["%s%s_weight" % (self._prefix, group)] = nd.concat(
                *ws, dim=0) if len(ws) > 1 else ws[0]
            args["%s%s_bias" % (self._prefix, group)] = nd.concat(
                *bs, dim=0) if len(bs) > 1 else bs[0]
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll across `length` steps (reference: rnn_cell.py:296)."""
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        self._set_begin_ref(inputs[0])
        if begin_state is None:
            begin_state = self.begin_state()
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states

    def _set_begin_ref(self, ref, batch_axis=0):
        self._begin_ref = ref
        self._begin_axis = batch_axis
        for cell in getattr(self, "_cells", ()):
            cell._set_begin_ref(ref, batch_axis)
        base = getattr(self, "base_cell", None)
        if base is not None:
            base._set_begin_ref(ref, batch_axis)

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell: act(W_i x + W_h h) (reference: rnn_cell.py:362)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference: rnn_cell.py:408; gate order i, f, c, o —
    the cuDNN/fused layout, matching ops/rnn.py)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        from .. import initializer as init

        self._iB = self.params.get(
            "i2h_bias",
            init=init.LSTMBias(forget_bias=forget_bias)
            if hasattr(init, "LSTMBias") else None)
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_i", "_f", "_c", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%si2h" % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name="%sh2h" % name)
        gates = list(symbol.SliceChannel(i2h + h2h, num_outputs=4,
                                         name="%sslice" % name))
        in_gate = symbol.Activation(gates[0], act_type="sigmoid")
        forget_gate = symbol.Activation(gates[1], act_type="sigmoid")
        in_transform = symbol.Activation(gates[2], act_type="tanh")
        out_gate = symbol.Activation(gates[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell, cuDNN formulation (reference: rnn_cell.py:469; gate
    order r, z, n matching ops/rnn.py)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_r", "_z", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%s_i2h" % name)
        h2h = symbol.FullyConnected(data=prev_h, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name="%s_h2h" % name)
        i2h_r, i2h_z, i2h_n = list(symbol.SliceChannel(
            i2h, num_outputs=3, name="%s_i2h_slice" % name))
        h2h_r, h2h_z, h2h_n = list(symbol.SliceChannel(
            h2h, num_outputs=3, name="%s_h2h_slice" % name))
        reset = symbol.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = symbol.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = symbol.Activation(i2h_n + reset * h2h_n,
                                       act_type="tanh")
        next_h = (1.0 - update) * next_h_tmp + update * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN over the `RNN` op (reference:
    rnn_cell.py:536 wrapping cuDNN; here the op is the lax.scan kernel in
    ops/rnn.py — one packed parameter vector, TNC compute layout)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        from .. import initializer as init

        self._parameter = self.params.get(
            "parameters",
            init=init.FusedRNN(None, num_hidden, num_layers, mode,
                               bidirectional, forget_bias))
        self._directions = 2 if bidirectional else 1

    @property
    def state_info(self):
        b = self._num_layers * self._directions
        info = [{"shape": (b, 0, self._num_hidden), "__layout__": "LNC"}]
        if self._mode == "lstm":
            info.append({"shape": (b, 0, self._num_hidden),
                         "__layout__": "LNC"})
        return info

    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot be stepped — use unroll() "
                         "(reference behavior)")

    def begin_state(self, func=None, **kwargs):
        if func is not None:
            return super().begin_state(func=func, **kwargs)
        ref = self._begin_ref
        if ref is None:
            raise MXNetError("FusedRNNCell.begin_state needs unroll() "
                             "(batch derives from the data symbol)")
        n = self._num_layers * self._directions
        axis = getattr(self, "_begin_axis", 1)
        states = [symbol._rnn_fused_state_zeros(
            ref, num_directions_layers=n, state_size=self._num_hidden,
            batch_axis=axis)]
        if self._mode == "lstm":
            states.append(symbol._rnn_fused_state_zeros(
                ref, num_directions_layers=n,
                state_size=self._num_hidden, batch_axis=axis))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        # fused op computes in TNC
        inputs, _ = _normalize_sequence(length, inputs, layout, True,
                                        in_layout=layout)
        if layout == "NTC":
            inputs = symbol.transpose(inputs, axes=(1, 0, 2))
        self._set_begin_ref(inputs, batch_axis=1)
        if begin_state is None:
            begin_state = self.begin_state()
        outs = symbol.RNN(
            inputs, self._parameter, begin_state[0],
            *(begin_state[1:] if self._mode == "lstm" else []),
            state_size=self._num_hidden, num_layers=self._num_layers,
            bidirectional=self._bidirectional, mode=self._mode,
            p=self._dropout, state_outputs=self._get_next_state,
            name="%srnn" % self._prefix)
        outs = list(outs) if self._get_next_state else \
            [outs if isinstance(outs, symbol.Symbol) else outs[0]]
        output = outs[0]
        if layout == "NTC":
            output = symbol.transpose(output, axes=(1, 0, 2))
        states = outs[1:] if self._get_next_state else []
        if merge_outputs is False:
            output = list(symbol.SliceChannel(
                output, num_outputs=length, axis=layout.find("T"),
                squeeze_axis=True))
        return output, states

    def unpack_weights(self, args):
        """Flat parameter vector -> per-layer/gate matrices (layout:
        ops/rnn.py _unpack — all wx/wh blocks, then all biases)."""
        import numpy as np

        args = args.copy()
        arr = args.pop(self._prefix + "parameters").asnumpy()
        from ..ops.rnn import _GATES

        G, H = _GATES[self._mode], self._num_hidden
        dirs = self._directions
        from .. import ndarray as nd

        def per_gate(pre, group, block, width):
            """Split a (G*H, width) block / (G*H,) bias into per-gate
            entries — the readable form the reference documents
            (i/f/c/o for lstm)."""
            for j, gate in enumerate(self._gate_names):
                part = block[j * H:(j + 1) * H]
                args["%s%s%s_%s" % (pre, group, gate,
                                    "weight" if width else "bias")] = \
                    nd.array(part)

        off = 0
        for layer in range(self._num_layers):
            in_sz = self._infer_input_size(arr) if layer == 0 \
                else self._num_hidden * dirs
            for d in range(dirs):
                pre = "%s%s%d_" % (self._prefix,
                                   "l" if d == 0 else "r", layer)
                wx = arr[off:off + G * H * in_sz].reshape(G * H, in_sz)
                off += G * H * in_sz
                wh = arr[off:off + G * H * H].reshape(G * H, H)
                off += G * H * H
                per_gate(pre, "i2h", wx, True)
                per_gate(pre, "h2h", wh, True)
        for layer in range(self._num_layers):
            for d in range(dirs):
                pre = "%s%s%d_" % (self._prefix,
                                   "l" if d == 0 else "r", layer)
                per_gate(pre, "i2h", arr[off:off + G * H], False)
                off += G * H
                per_gate(pre, "h2h", arr[off:off + G * H], False)
                off += G * H
        return args

    def _infer_input_size(self, arr):
        """Solve the flat size for the layer-0 input width (reference
        derives it the same way from the parameter count)."""
        from ..ops.rnn import _GATES, rnn_param_size

        G, H, dirs = (_GATES[self._mode], self._num_hidden,
                      self._directions)
        rest = rnn_param_size(self._num_layers, 0, H,
                              self._bidirectional, self._mode)
        return (arr.size - rest) // (dirs * G * H)

    def pack_weights(self, args):
        """Per-gate matrices -> the flat parameter vector, inverting
        unpack_weights (same block order as ops/rnn.py _unpack: all
        wx/wh per (layer, direction), then all biases)."""
        import numpy as np

        from .. import ndarray as nd

        args = args.copy()
        dirs = self._directions

        def pop_gates(pre, group, kind):
            return np.concatenate(
                [np.asarray(args.pop("%s%s%s_%s" % (pre, group, gate,
                                                    kind)).asnumpy())
                 .reshape(-1 if kind == "bias" else
                          (self._num_hidden, -1)).reshape(-1)
                 for gate in self._gate_names])

        chunks = []
        for layer in range(self._num_layers):
            for d in range(dirs):
                pre = "%s%s%d_" % (self._prefix,
                                   "l" if d == 0 else "r", layer)
                chunks.append(pop_gates(pre, "i2h", "weight"))
                chunks.append(pop_gates(pre, "h2h", "weight"))
        for layer in range(self._num_layers):
            for d in range(dirs):
                pre = "%s%s%d_" % (self._prefix,
                                   "l" if d == 0 else "r", layer)
                chunks.append(pop_gates(pre, "i2h", "bias"))
                chunks.append(pop_gates(pre, "h2h", "bias"))
        args[self._prefix + "parameters"] = nd.array(
            np.concatenate(chunks).astype(np.float32))
        return args

    def unfuse(self):
        """Equivalent SequentialRNNCell of unfused cells (reference:
        rnn_cell.py unfuse)."""
        stack = SequentialRNNCell()
        make = {"rnn_relu": lambda p: RNNCell(self._num_hidden,
                                              activation="relu", prefix=p),
                "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                              activation="tanh", prefix=p),
                "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p,
                                           forget_bias=self._forget_bias),
                "gru": lambda p: GRUCell(self._num_hidden, prefix=p)}[
                    self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    make("%sl%d_" % (self._prefix, i)),
                    make("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_%d_" % (self._prefix, i)))
            else:
                stack.add(make("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix="%s_dropout%d_"
                                      % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in order (reference: rnn_cell.py:748)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, (
                "Either specify params for SequentialRNNCell or child "
                "cells, not both.")
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return _cells_state_info(self._cells)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._cells, **kwargs)

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Delegate to each child's unroll (reference behavior) so
        unroll-only children (FusedRNNCell, BidirectionalCell) compose."""
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        self._set_begin_ref(inputs[0])
        if begin_state is None:
            begin_state = self.begin_state()
        p = 0
        next_states = []
        num_cells = len(self._cells)
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Dropout on cell outputs (reference: rnn_cell.py:827)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(inputs, p=self.dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell (reference: rnn_cell.py:867)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError()


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference: rnn_cell.py:909): randomly
    keeps previous states in place of new ones during training."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), (
            "FusedRNNCell does not support zoneout; unfuse() first.")
        assert not isinstance(base_cell, BidirectionalCell), (
            "BidirectionalCell does not support zoneout; apply zoneout to "
            "the inner cells instead.")
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell = self.base_cell
        next_output, next_states = cell(inputs, states)
        po, ps = self.zoneout_outputs, self.zoneout_states

        def mask(p, like):
            # Dropout emits a (scaled) Bernoulli keep-mask of `like`'s
            # shape — the reference builds the mask the same way
            return symbol.Dropout(symbol.ones_like(like), p=p)

        prev_output = self.prev_output if self.prev_output is not None \
            else symbol.zeros_like(next_output)
        output = symbol.where(mask(po, next_output), next_output,
                              prev_output) if po > 0 else next_output
        states = [symbol.where(mask(ps, ns), ns, s)
                  for ns, s in zip(next_states, states)] if ps > 0 \
            else next_states
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Adds the input to the cell output (reference: rnn_cell.py:957)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return symbol.elemwise_add(output, inputs), states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        merge = isinstance(outputs, symbol.Symbol) \
            if merge_outputs is None else merge_outputs
        inputs, _ = _normalize_sequence(length, inputs, layout, merge)
        if merge:
            outputs = symbol.elemwise_add(outputs, inputs)
        else:
            outputs = [symbol.elemwise_add(o, i)
                       for o, i in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(BaseRNNCell):
    """Forward + backward cells over the sequence (reference:
    rnn_cell.py:998). Only usable through unroll()."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._output_prefix = output_prefix
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped — use "
                         "unroll() (reference behavior)")

    @property
    def state_info(self):
        return _cells_state_info(self._cells)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._cells, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, False)
        self._set_begin_ref(inputs[0])
        if begin_state is None:
            begin_state = self.begin_state()
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=begin_state[:n_l],
            layout=layout, merge_outputs=merge_outputs)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=begin_state[n_l:], layout=layout,
            merge_outputs=False)
        r_outputs = list(reversed(r_outputs))
        if merge_outputs is None:
            merge_outputs = isinstance(l_outputs, symbol.Symbol)
        l_list, _ = _normalize_sequence(length, l_outputs, layout, False)
        outputs = [symbol.Concat(l, r, dim=1,
                                 name="%st%d" % (self._output_prefix, i))
                   for i, (l, r) in enumerate(zip(l_list, r_outputs))]
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, l_states + r_states
