"""mx.rnn — the legacy symbolic RNN cell API + bucketing iterator.

Reference: python/mxnet/rnn/ (rnn_cell.py symbolic cells, io.py
BucketSentenceIter, rnn.py checkpoint helpers) — the API behind
example/rnn/bucketing. Gluon users should prefer mxnet_tpu.gluon.rnn;
this namespace exists so reference RNN training scripts port with only
the import line changed.
"""
from .rnn_cell import (RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, DropoutCell,
                       ModifierCell, ZoneoutCell, ResidualCell,
                       BidirectionalCell)
from .io import encode_sentences, BucketSentenceIter
from .rnn import (save_rnn_checkpoint, load_rnn_checkpoint,
                  do_rnn_checkpoint)

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "DropoutCell",
           "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell", "encode_sentences", "BucketSentenceIter",
           "save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]
