"""Optimizers (reference: python/mxnet/optimizer/optimizer.py, 1.8k LoC).

Same API as the reference: an `Optimizer` registry, per-index lr/wd
multipliers, `create_state`/`update`, and an `Updater` for local updates
(optimizer.py:1621). Every update lowers onto the fused update ops in
ops/optimizer_ops.py — one XLA kernel per (op, hyperparams), with the
functional outputs written back into weight/state buffers (the TPU version of
the reference's in-place kernels src/operator/optimizer_op.cc)."""
from __future__ import annotations

import pickle

import numpy as _np

from .base import _Registry, MXNetError
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdaGrad", "AdaDelta", "RMSProp",
           "Ftrl", "FTML", "Signum", "SGLD", "DCASGD", "Adamax", "Nadam",
           "AdamW", "LBSGD", "Updater", "get_updater", "create", "register"]

_REG = _Registry("optimizer")


def register(klass):
    _REG.register(klass, klass.__name__)
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _REG.create(name, **kwargs)


class Optimizer:
    """Base optimizer (reference: optimizer.py:46)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.sym_info = ()

    # -- registry-compatible helpers --------------------------------------
    @staticmethod
    def create_optimizer(name, **kwargs):
        return create(name, **kwargs)

    def create_state(self, index, weight):
        return None

    @staticmethod
    def _is_half(dtype):
        """float16 OR bfloat16 — on TPU bf16 is the half-precision training
        dtype (the MXU's native input type), so multi_precision master
        weights must cover it too (reference handles fp16 only:
        optimizer.py multi-precision SGD)."""
        return str(_np.dtype(dtype) if dtype is not None else None) in (
            "float16", "bfloat16")

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and self._is_half(weight.dtype):
            w32 = weight.astype("float32")
            return (self.create_state(index, w32), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    #: set True on optimizers with a lazy row_sparse update kernel
    #: (reference: sgd/adam/adagrad Rsp impls in src/operator/optimizer_op.cc)
    supports_sparse = False

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and self._is_half(weight.dtype):
            half = str(_np.dtype(weight.dtype))
            s, w32 = state
            g32 = grad.astype("float32")
            self.update(index, w32, g32, s)
            weight._set_data(w32.astype(half)._data)
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("lr_scheduler is set; cannot override lr")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _common(self, index):
        self._update_count(index)
        return dict(lr=self._get_lr(index), wd=self._get_wd(index),
                    rescale_grad=self.rescale_grad,
                    clip_gradient=self.clip_gradient if self.clip_gradient else -1.0)

    def __getstate__(self):
        d = self.__dict__.copy()
        d["param_dict"] = {}
        return d


@register
class SGD(Optimizer):
    """SGD(+momentum, multi-precision) — reference optimizer.py:511."""

    supports_sparse = True

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype="float32")

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        if _is_row_sparse(grad):
            if self.lazy_update:
                # lazy row update (reference: SGDUpdateRspImpl
                # optimizer_op.cc; untouched rows skip wd/momentum)
                from .ndarray import sparse as _sp

                if state is None:
                    _sp.sgd_update(weight, grad, **kw)
                else:
                    _sp.sgd_mom_update(weight, grad, state,
                                       momentum=self.momentum, **kw)
                return
            grad = grad.tostype("default")  # lazy_update=False: std update
        if state is None:
            nd.sgd_update(weight, grad, out=weight, **kw)
        else:
            nd.sgd_mom_update(weight, grad, state, out=[weight, state],
                              momentum=self.momentum, **kw)


def _is_row_sparse(arr):
    from .ndarray.sparse import RowSparseNDArray

    return isinstance(arr, RowSparseNDArray)


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype="float32")

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        if state is None:
            nd.sgd_update(weight, grad, out=weight, **kw)
        else:
            nd.nag_mom_update(weight, grad, state, out=[weight, state],
                              momentum=self.momentum, **kw)


@register
class Adam(Optimizer):
    supports_sparse = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype="float32"),
                nd.zeros(weight.shape, ctx=weight.context, dtype="float32"))

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        t = self._index_update_count[index]
        mean, var = state
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        kw["lr"] = kw["lr"] * (coef2 ** 0.5) / coef1
        if _is_row_sparse(grad):
            if self.lazy_update:
                from .ndarray import sparse as _sp

                _sp.adam_update(weight, grad, mean, var, beta1=self.beta1,
                                beta2=self.beta2, epsilon=self.epsilon, **kw)
                return
            grad = grad.tostype("default")  # lazy_update=False: std update
        nd.adam_update(weight, grad, mean, var, out=[weight, mean, var],
                       beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon, **kw)


@register
class AdamW(Optimizer):
    """Decoupled weight decay (reference: contrib adamw.cc + adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 eta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon, self.eta = beta1, beta2, epsilon, eta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype="float32"),
                nd.zeros(weight.shape, ctx=weight.context, dtype="float32"))

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        mean, var = state
        nd.adamw_update(weight, grad, mean, var, out=[weight, mean, var],
                        beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                        eta=self.eta, **kw)


@register
class AdaGrad(Optimizer):
    supports_sparse = True

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context, dtype="float32")

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        if _is_row_sparse(grad):
            from .ndarray import sparse as _sp

            _sp.adagrad_update(weight, grad, state,
                               epsilon=self.float_stable_eps, **kw)
            return
        nd.adagrad_update(weight, grad, state, out=[weight, state],
                          epsilon=self.float_stable_eps, **kw)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype="float32"),
                nd.zeros(weight.shape, ctx=weight.context, dtype="float32"))

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        kw.pop("lr")
        acc_g, acc_d = state
        nd.adadelta_update(weight, grad, acc_g, acc_d, out=[weight, acc_g, acc_d],
                           rho=self.rho, epsilon=self.epsilon, **kw)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9, epsilon=1e-8,
                 centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, ctx=weight.context, dtype="float32")
        if self.centered:
            return (z(), z(), z())
        return z()

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        kw["clip_weights"] = self.clip_weights if self.clip_weights else -1.0
        if self.centered:
            n, g, delta = state
            nd.rmspropalex_update(weight, grad, n, g, delta,
                                  out=[weight, n, g, delta], gamma1=self.gamma1,
                                  gamma2=self.gamma2, epsilon=self.epsilon, **kw)
        else:
            nd.rmsprop_update(weight, grad, state, out=[weight, state],
                              gamma1=self.gamma1, epsilon=self.epsilon, **kw)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype="float32"),
                nd.zeros(weight.shape, ctx=weight.context, dtype="float32"))

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        z, n = state
        nd.ftrl_update(weight, grad, z, n, out=[weight, z, n],
                       lamda1=self.lamda1, beta=self.beta, **kw)


@register
class FTML(Optimizer):
    def __init__(self, beta1=0.6, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, ctx=weight.context, dtype="float32")
        return (z(), z(), z())

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        kw["clip_grad"] = kw.pop("clip_gradient")
        d, v, z = state
        t = self._index_update_count[index]
        nd.ftml_update(weight, grad, d, v, z, out=[weight, d, v, z],
                       beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                       t=t, **kw)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype="float32")

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        if state is None:
            nd.signsgd_update(weight, grad, out=weight, **kw)
        else:
            nd.signum_update(weight, grad, state, out=[weight, state],
                             momentum=self.momentum, wd_lh=self.wd_lh, **kw)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference: optimizer.py:1083)."""

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        g = grad * kw["rescale_grad"]
        if kw["clip_gradient"] > 0:
            g = g.clip(-kw["clip_gradient"], kw["clip_gradient"])
        noise = nd.random.normal(loc=0, scale=float(_np.sqrt(kw["lr"])),
                                 shape=weight.shape)
        weight._set_data((weight - kw["lr"] / 2 * (g + kw["wd"] * weight) + noise)._data)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py:975)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = None if self.momentum == 0.0 else nd.zeros(weight.shape, ctx=weight.context)
        return (mom, weight.copy())

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        g = grad * kw["rescale_grad"]
        if kw["clip_gradient"] > 0:
            g = g.clip(-kw["clip_gradient"], kw["clip_gradient"])
        mom, prev = state
        comp = g + kw["wd"] * weight + self.lamda * g * g * (weight - prev)
        if mom is None:
            delta = -kw["lr"] * comp
        else:
            mom._set_data((self.momentum * mom - kw["lr"] * comp)._data)
            delta = mom
        prev._set_data(weight._data)
        weight._set_data((weight + delta)._data)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype="float32"),
                nd.zeros(weight.shape, ctx=weight.context, dtype="float32"))

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        t = self._index_update_count[index]
        lr = kw["lr"] / (1.0 - self.beta1 ** t)
        m, u = state
        g = grad * kw["rescale_grad"] + kw["wd"] * weight
        if kw["clip_gradient"] > 0:
            g = g.clip(-kw["clip_gradient"], kw["clip_gradient"])
        m._set_data((self.beta1 * m + (1.0 - self.beta1) * g)._data)
        u._set_data(nd.maximum(self.beta2 * u, g.abs())._data)
        weight._set_data((weight - lr * m / (u + 1e-8))._data)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype="float32"),
                nd.zeros(weight.shape, ctx=weight.context, dtype="float32"))

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        t = self._index_update_count[index]
        g = grad * kw["rescale_grad"] + kw["wd"] * weight
        if kw["clip_gradient"] > 0:
            g = g.clip(-kw["clip_gradient"], kw["clip_gradient"])
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self.schedule_decay))
        self.m_schedule *= momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m, v = state
        m._set_data((self.beta1 * m + (1.0 - self.beta1) * g)._data)
        v._set_data((self.beta2 * v + (1.0 - self.beta2) * g * g)._data)
        g_prime = g / (1.0 - self.m_schedule)
        m_prime = m / (1.0 - m_schedule_next)
        v_prime = v / (1.0 - self.beta2 ** t)
        m_bar = (1.0 - momentum_t) * g_prime + momentum_t_1 * m_prime
        weight._set_data((weight - kw["lr"] * m_bar / (v_prime.sqrt() + self.epsilon))._data)


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style warmup (reference: optimizer.py:782).
    Layer-wise adaptive rate: lr scaled by ||w||/||g||."""

    def __init__(self, momentum=0.0, warmup_strategy="linear", warmup_epochs=5,
                 batch_scale=1, updates_per_epoch=32, begin_epoch=0, num_epochs=60,
                 **kwargs):
        super().__init__(momentum=momentum, **kwargs)
        self.warmup_strategy = warmup_strategy

    def update(self, index, weight, grad, state):
        kw = self._common(index)
        wnorm = float(weight.norm().asscalar())
        gnorm = float(grad.norm().asscalar())
        if wnorm > 0 and gnorm > 0:
            kw["lr"] = kw["lr"] * min(wnorm / (gnorm * kw["rescale_grad"] + 1e-12), 10.0)
        if state is None:
            nd.sgd_update(weight, grad, out=weight, **kw)
        else:
            nd.sgd_mom_update(weight, grad, state, out=[weight, state],
                              momentum=self.momentum, **kw)


ccSGD = SGD  # legacy alias (reference registers ccSGD -> SGD)
_REG.register(SGD, "ccsgd")


class Updater:
    """Local updater applying Optimizer with per-index states
    (reference: optimizer.py:1621 get_updater)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad, self.states[index])

    def set_states(self, states):
        def _nd_state(s):
            # inverse of get_states' _np_state: rehydrate numpy leaves into
            # NDArray (dtype preserved — momentum may be fp16/bf16). Leaving
            # numpy in self.states crashed the first post-restore update
            # (the jitted optimizer kernels key on NDArray inputs).
            if isinstance(s, _np.ndarray):
                return nd.array(s, dtype=s.dtype)
            if isinstance(s, (tuple, list)):
                return tuple(_nd_state(x) for x in s)
            return s

        data = pickle.loads(states)
        if isinstance(data, tuple) and len(data) == 2:
            loaded, opt_state = data
            self.optimizer.__dict__.update(opt_state)
        else:
            loaded = data
        self.states = {k: _nd_state(v) for k, v in loaded.items()}
        self.states_synced = {k: True for k in self.states}

    def get_states(self, dump_optimizer=False):
        def _np_state(s):
            if isinstance(s, NDArray):
                return s.asnumpy()
            if isinstance(s, (tuple, list)):
                return tuple(_np_state(x) for x in s)
            return s

        states = {k: _np_state(v) for k, v in self.states.items()}
        if dump_optimizer:
            return pickle.dumps((states, self.optimizer.__getstate__()))
        return pickle.dumps(states)


def get_updater(optimizer):
    return Updater(optimizer)
