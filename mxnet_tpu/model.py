"""Checkpoint format + legacy FeedForward estimator.

Reference: python/mxnet/model.py — save_checkpoint :394 writes
`prefix-symbol.json` (graph JSON) + `prefix-%04d.params` (binary NDArray
dict with arg:/aux: prefixes); load_checkpoint :424; FeedForward :462 is
the pre-Module estimator API, kept as a thin veneer over Module.
"""
from __future__ import annotations

import logging

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym_mod

__all__ = ["save_checkpoint", "load_checkpoint", "load_params",
           "FeedForward", "BatchEndParam"]

from .module.base_module import BatchEndParam  # noqa: E402 (re-export)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """reference: model.py:394."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    nd.save("%s-%04d.params" % (prefix, epoch), save_dict)
    logging.info("Saved checkpoint to \"%s-%04d.params\"", prefix, epoch)


def load_params(fname):
    """Split an arg:/aux: prefixed params file (reference: model.py:424).
    `fname` may also be raw file bytes (the C predict API passes params
    in-memory — c_predict_api.h MXPredCreate param_bytes)."""
    loaded = nd.load(fname)
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """reference: model.py:424 — (symbol, arg_params, aux_params)."""
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params("%s-%04d.params" % (prefix, epoch))
    return symbol, arg_params, aux_params


class FeedForward(object):
    """Legacy estimator (reference: model.py:462). Deprecated in the
    reference in favor of Module; provided as a Module veneer."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from .initializer import Uniform

        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer or Uniform(0.01)
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self.kwargs = kwargs
        self._module = None

    def _mod(self, data, label_names=("softmax_label",)):
        from .module import Module

        if self._module is None:
            self._module = Module(self.symbol, context=self.ctx,
                                  label_names=list(label_names))
        return self._module

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        mod = self._mod(X)
        mod.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=dict(self.kwargs.get("optimizer_params",
                                                      {"learning_rate": 0.01})),
                initializer=self.initializer, arg_params=self.arg_params,
                aux_params=self.aux_params, begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch)
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        mod = self._mod(X)
        if not mod.binded:
            mod.bind(X.provide_data, X.provide_label, for_training=False)
            mod.init_params(self.initializer, self.arg_params, self.aux_params,
                            allow_missing=False)
        out = mod.predict(X, num_batch=num_batch, reset=reset)
        return out.asnumpy() if hasattr(out, "asnumpy") else out

    def save(self, prefix, epoch=None):
        save_checkpoint(prefix, epoch if epoch is not None else self.num_epoch,
                        self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)
