"""Typed registry of every ``MXTPU_*`` environment variable.

The reference framework read its ~71 ``MXNET_*`` knobs through one choke
point (``dmlc::GetEnv`` — typed, defaulted, greppable). Three generations of
runtime machinery here (Pallas fusion, elastic fault tolerance, telemetry)
had instead accumulated ad-hoc ``os.environ`` reads scattered across the
library, and the docs table drifted from the code. This module is the single
authority: every MXTPU variable is declared once — name, type, default,
documentation — and library code reads it through the typed accessors below.

Static enforcement: ``ci/mxlint``'s ``env-registry`` checker fails the tree
when library code reads an ``MXTPU_*`` name through raw ``os.environ`` /
``os.getenv``, when a read name is missing from this registry, or when the
registry and the ``docs/env_vars.md`` table disagree (the table's Framework
section is GENERATED from this registry: ``python -m mxnet_tpu.env
--markdown``).

Accessors (registered names only — an unregistered name raises ``KeyError``
eagerly, the runtime arm of the lint guarantee):

  * ``raw(name)``    -> exactly ``os.environ.get(name)`` (``None`` if unset)
    — for call sites with bespoke parsing (tri-state gates, on/off synonym
    sets) that must keep their historical semantics bit-for-bit.
  * ``is_set(name)`` -> set to a non-empty string.
  * ``get(name, default=...)`` -> value parsed per the registered type, with
    the registered default (or the per-call override) when unset or
    malformed. Malformed-falls-back matches the library's defensive reads
    (a typo'd ``MXTPU_FLIGHTREC_EVENTS`` must not take training down).

Types: ``str`` (returned verbatim), ``int`` / ``float`` (parsed, fallback on
``ValueError``), ``bool`` (unset/empty/``0``/``false``/``off``/``no`` are
False, anything else True — the superset of the ``not in ("", "0")`` idiom
the scattered reads used).

Pure stdlib, imports nothing from the package — ``telemetry.core`` (which
must stay jax/numpy-free) imports it during early package init.
"""
from __future__ import annotations

import os

__all__ = ["EnvVar", "registry", "names", "raw", "is_set", "get",
           "markdown_table"]

_FALSY = ("", "0", "false", "off", "no")


class EnvVar:
    """One registered variable: name, type, default, documentation."""

    __slots__ = ("name", "vtype", "default", "doc")

    def __init__(self, name, vtype, default, doc):
        self.name = name
        self.vtype = vtype
        self.default = default
        self.doc = doc

    def parse(self, value):
        """Parse a raw env string per this var's type; ValueError on a
        value the type can't hold (``get`` turns that into the default)."""
        if self.vtype == "bool":
            return value.strip().lower() not in _FALSY
        if self.vtype == "int":
            return int(value)
        if self.vtype == "float":
            return float(value)
        return value

    def default_str(self):
        """Rendering of the default for the generated docs table."""
        if self.default is None:
            return "unset"
        if self.vtype == "bool":
            return "`1`" if self.default else "`0`"
        return "`%s`" % (self.default,)


_REGISTRY: dict = {}  # name -> EnvVar, insertion-ordered (= docs-table order)


def _var(name, vtype, default, doc):
    assert name.startswith("MXTPU_") and name not in _REGISTRY, name
    _REGISTRY[name] = EnvVar(name, vtype, default, doc)


def registry():
    """The full name -> EnvVar mapping (insertion-ordered copy)."""
    return dict(_REGISTRY)


def names():
    """Registered names, in declaration (= documentation) order."""
    return list(_REGISTRY)


def _check(name):
    var = _REGISTRY.get(name)
    if var is None:
        raise KeyError(
            "environment variable %r is not in the mxnet_tpu.env registry; "
            "declare it there (with type/default/doc) before reading it"
            % (name,))
    return var


def raw(name):
    """``os.environ.get(name)`` for a registered name (None when unset)."""
    _check(name)
    return os.environ.get(name)


def is_set(name):
    """Registered name is set to a non-empty string."""
    _check(name)
    return bool(os.environ.get(name))


_UNSET = object()


def get(name, default=_UNSET):
    """Typed read: parse per the registered type; the registered default
    (or the per-call ``default`` override) when unset or malformed."""
    var = _check(name)
    fallback = var.default if default is _UNSET else default
    value = os.environ.get(name)
    if value is None:
        return fallback
    try:
        return var.parse(value)
    except ValueError:
        return fallback


# ---------------------------------------------------------------------------
# the registry — declaration order is the docs/env_vars.md table order
# ---------------------------------------------------------------------------

# -- runtime / compile ------------------------------------------------------
_var("MXTPU_NO_NATIVE", "bool", False,
     "Disable the native C++ runtime (recordio/prefetch/buffer pool); "
     "pure-Python fallbacks are used.")
_var("MXTPU_COMPILE_CACHE", "str", None,
     "Persistent tier of the unified executable cache "
     "(`mxnet_tpu.compile`, docs/compile_cache.md): a directory path, or "
     "`1` for the repo-local `.mxtpu_compile_cache` default; "
     "`0`/`off`/`none` (or unset) disables. Compiled executables are "
     "serialized per (key x shapes x dtypes x jax version x backend) with "
     "crc-verified atomic-rename artifacts, so a restarted serving "
     "replica / elastic-restart generation / repeat bench run reaches "
     "steady state with zero recompiles. Not default-on: artifacts are "
     "machine-scoped (XLA:CPU AOT reloads across machine-feature "
     "mismatches risk SIGILL) and the directory must be trusted "
     "(artifacts unpickle on load). `bench.py` arms it for accelerator "
     "runs; manage with `python -m mxnet_tpu.compile`.")
_var("MXTPU_COMPILE_CACHE_ENTRIES", "int", 4096,
     "Capacity of the unified executable cache's in-memory LRU table "
     "(`mxnet_tpu.compile.registry`): oldest-touched executables are "
     "evicted past this many entries "
     "(`mxtpu_compile_cache_evict_total`).")
_var("MXTPU_JAX_COMPILE_CACHE", "str", None,
     "Optional extra knob: arm jax's OWN persistent compilation cache "
     "(`jax_compilation_cache_dir`, keyed by HLO+backend) at the given "
     "directory, `1` for the repo-local `.jax_cache` default "
     "(`base.enable_persistent_compile_cache`). Independent of — and "
     "composable with — the `MXTPU_COMPILE_CACHE` executable-artifact "
     "tier: jax's cache skips XLA backend compilation but still pays "
     "trace+lower per process; the artifact tier skips everything.")
_var("MXTPU_SHARDED_STEP", "bool", False,
     "Promote user-facing training loops onto the fused whole-step "
     "executable (forward + loss + backward + optimizer update as ONE "
     "jit with donated param/state buffers, docs/sharded_training.md): "
     "`gluon.Trainer(..., block=)` internally becomes a "
     "`parallel.ShardedTrainer`, and `module.fit()` routes each step "
     "through `Module.fused_step` — no model-code changes. Fused keys "
     "carry a device-topology fingerprint, so with "
     "`MXTPU_COMPILE_CACHE` armed their executables persist and a "
     "restarted run reaches step 1 with zero `jit_compile` events. "
     "Exported fleet-wide by `tools/launch.py --sharded-step`.")
_var("MXTPU_SHARDED_PREFETCH", "bool", True,
     "On the first fused-step cache miss, batch-stage every artifact "
     "listed in the trainer's warmup manifest from the persistent tier "
     "before building (`compile.prefetch`): a restarted generation "
     "loads its whole executable set in one pass instead of "
     "one-disk-probe-per-shape. `0` falls back to per-key probing.")
_var("MXTPU_PY_RECORDIO", "bool", False,
     "Force the Python recordio reader/writer even when the native library "
     "is built (used by rec2idx for `tell()` positions).")

# -- fused kernels ----------------------------------------------------------
_var("MXTPU_PALLAS_LSTM", "str", "auto",
     "Fused Pallas LSTM layer (`ops/pallas_kernels.lstm_layer`): `auto` = "
     "on for TPU, `1` forces it everywhere (interpret mode on CPU — "
     "tests), `0` disables (lax.scan fallback).")
_var("MXTPU_PALLAS_CONV_EPILOGUE", "str", "auto",
     "Fused conv-epilogue kernels (BN batch-stats + normalize + ReLU + "
     "residual add as one Pallas kernel pair, `ops/pallas_kernels."
     "conv_epilogue`): `auto` = on for single-device TPU runs (pallas_call "
     "has no SPMD partitioning rule, so sharded multi-device runs keep the "
     "jnp psum sync-BN path), `1` forces it everywhere (interpret mode on "
     "CPU — tests; any device count), `0` disables (pure-jnp custom-vjp BN "
     "+ separate add/relu). Channels-last (NHWC) training path only; "
     "channels-first always uses the jnp fallback. Any non-`0` value also "
     "makes the model-zoo ResNets BUILD the fused graph (BatchNormRelu/"
     "BatchNormAddRelu ops; parameter names unchanged). Read at first "
     "compile of each op/attrs combination — flip it between processes (as "
     "`tools/bench_capture.sh` A-B rows do), not mid-process.")
_var("MXTPU_PALLAS_DECODE", "str", "auto",
     "Paged decode-attention kernel (`ops/pallas_kernels.paged_attention` "
     "— flash-decode, q_len=1 against the block-allocated KV cache, page "
     "tables via scalar prefetch): `auto` = kernel on TPU, dense-gather "
     "jnp fallback elsewhere; `1` forces the kernel everywhere (interpret "
     "mode on CPU — parity tests); `0` forces the jnp path. Read at trace "
     "time of each decode executable — flip it between processes, not "
     "mid-process.")
_var("MXTPU_S2D_STEM", "bool", False,
     "`1` builds model-zoo ResNets with the space-to-depth stem (7×7/s2 "
     "over 3ch → 4×4/s1 over 12ch; weight-space transform `resnet."
     "stem_weight_to_s2d`, checkpoint converter `resnet."
     "convert_stem_params`).")

# -- profiler ---------------------------------------------------------------
_var("MXTPU_PROFILE_SYNC", "bool", False,
     "Profiler records true device time by blocking per op, instead of "
     "(async) dispatch time. Equivalent of the reference engine's "
     "profiling stamps.")
_var("MXTPU_STEP_TRACE_DIR", "str", "step_trace",
     "Output directory for `tools/step_profile.py` XLA (xplane) step "
     "traces.")

# -- bench.py ---------------------------------------------------------------
_var("MXTPU_BENCH_BATCH", "int", 32, "bench.py batch size.")
_var("MXTPU_BENCH_WARMUP", "int", 3, "bench.py warmup iterations.")
_var("MXTPU_BENCH_ITERS", "int", 10, "bench.py measured iterations.")
_var("MXTPU_BENCH_MODE", "str", "train",
     "bench.py mode: `train`, `score` (reference benchmark_score.py "
     "analogue), `score_int8` (quantize_model int8 deployment path), "
     "`bert` (BERT-base tokens/sec + MFU), `lstm` (word-LM), "
     "`train_sharded` (ShardedTrainer fused-step vs op-by-op A/B, "
     "docs/sharded_training.md), `goodput` (attribution self-check A/B), "
     "`train_input` (sync vs prefetched input-pipeline A/B, "
     "docs/data_pipeline.md).")
_var("MXTPU_BENCH_SHARDED_IMPL", "str", "fused",
     "train_sharded mode implementation under test: `fused` times BOTH "
     "the op-by-op baseline and the promoted fused step (the A/B row); "
     "`opbyop` times only the baseline (its own committed row).")
_var("MXTPU_BENCH_NET", "str", "resnet50",
     "model for train/score modes (`resnet152`, `inception_v3` for score; "
     "`inception_v3`, `alexnet` for train — the BASELINE.md V100 rows).")
_var("MXTPU_BENCH_LAYOUT", "str", "NCHW",
     "`NHWC` builds the bench net channels-last (layout_scope) and feeds "
     "NHWC batches.")
_var("MXTPU_BENCH_DTYPE", "str", "bfloat16",
     "bench compute precision (`float32` for the fp32 path).")
_var("MXTPU_BENCH_SEQLEN", "int", 512,
     "sequence length for the `bert` bench mode.")
_var("MXTPU_BENCH_DIAL_RETRY_S", "int", 900,
     "bench watchdog: total seconds to keep retrying a wedged accelerator "
     "dial before failing with a JSON error line.")
_var("MXTPU_BENCH_FORCE_DIAL_FAIL", "bool", False,
     "test hook: exercise the unreachable-device JSON contract (incl. the "
     "stale-capture fallback) without a wedged tunnel.")
_var("MXTPU_BENCH_SEGMENTS", "str", "1",
     "train-mode MFU segment decomposition (matmul ceiling / fwd / "
     "fwd+dgrad fields). `0` disables; `force` bypasses the TPU-only gate "
     "(contract tests).")
_var("MXTPU_BENCH_SEG_MM_N", "int", 8192,
     "matrix side for the segment matmul-ceiling measurement.")
_var("MXTPU_BENCH_SWEEP_BATCH", "int", 256,
     "large-batch sweep point 1 batch size (fields `sweep_*`; `0` "
     "disables).")
_var("MXTPU_BENCH_SWEEP_BATCH2", "int", 512,
     "large-batch sweep point 2 batch size (fields `sweep2_*`; `0` "
     "disables).")
_var("MXTPU_BENCH_PROFILE", "bool", False,
     "`1` captures an XLA (xplane) trace of a few steady-state bench steps "
     "next to the JSON artifact (the docs/perf_notes.md MFU-gap evidence "
     "path).")
_var("MXTPU_BENCH_PROFILE_DIR", "str", None,
     "Output directory for the `MXTPU_BENCH_PROFILE` trace (default "
     "`bench_trace_<mode>`).")
_var("MXTPU_BENCH_INPUT_STALL_MS", "int", 20,
     "train_input mode: per-batch producer stall (ms) of the deliberately "
     "input-bound workload the sync-vs-prefetched A/B runs against.")

# -- data loading -----------------------------------------------------------
_var("MXTPU_DATALOADER_CTX", "str", "fork",
     "multiprocessing start method for DataLoader worker processes "
     "(`spawn` needs a `__main__` guard).")
_var("MXTPU_DATALOADER_TIMEOUT", "float", 300.0,
     "seconds to wait for a worker batch before raising (dead-worker "
     "detection).")
_var("MXTPU_DATALOADER_PROBE_TIMEOUT", "float", 20.0,
     "seconds the DataLoader's worker-viability probe (one sample round-"
     "tripped through a real worker process) may take before the loader "
     "falls back to in-process loading; the legit probe path touches no "
     "jax and returns in well under a second.")
_var("MXTPU_DATA_PREFETCH", "bool", False,
     "`1` wraps the `module.fit` batch iterator in the mxnet_tpu.data "
     "DevicePrefetcher: batch N+1's host decode + async host->device copy "
     "overlap batch N's compute (docs/data_pipeline.md).")
_var("MXTPU_DATA_PREFETCH_DEPTH", "int", 2,
     "batches the DevicePrefetcher stages ahead (double-buffering). Depth "
     "d absorbs producer jitter up to d x step-time; sizing math in "
     "docs/data_pipeline.md.")
_var("MXTPU_DATA_JOIN_TIMEOUT_S", "float", 30.0,
     "seconds the data pipeline's close()/reset() wait for producer and "
     "decode-worker threads to stop before raising (rewinding reader "
     "state under a live reader would corrupt the next epoch).")

# -- test suite -------------------------------------------------------------
_var("MXTPU_TEST_TPU", "bool", False,
     "`1` lets the pytest conftest keep the real accelerator (the `-m "
     "tpu` smoke suite); default runs pin CPU.")
_var("MXTPU_TEST_SEED", "int", None,
     "fixed seed for `test_utils.with_seed` tests (printed on failure for "
     "replay; tools/flakiness_checker.py sets both this and "
     "`MXNET_TEST_SEED`).")
_var("MXTPU_TEST_EXAMPLES_FULL", "bool", False,
     "`1` runs the examples CI at full configs instead of the <60s smoke "
     "configs.")
_var("MXTPU_TEST_LARGE_FULL", "bool", False,
     "`1` runs the allocation-heavy (>2 GiB) large-tensor tests (the "
     "reference keeps these in tests/nightly); default runs keep only the "
     "allocation-free checks.")
_var("MXTPU_TEST_CONVERGENCE_FULL", "bool", False,
     "`1` runs the long eager convergence fits (SSD, NLP models) the "
     "default suite skips.")
_var("MXTPU_TEST_TOTAL_STEPS", "int", None,
     "resilience/flight-recorder test workers: total training steps "
     "(worker-specific defaults).")
_var("MXTPU_TEST_STEP_SLEEP", "float", 0.05,
     "flight-recorder test worker: per-step sleep (hang-detection "
     "timing base).")
_var("MXTPU_TEST_CKPT_EVERY", "int", 2,
     "resilience test worker: checkpoint period in steps.")
_var("MXTPU_WALLTIME_FILE", "str", None,
     "if set, the pytest conftest appends a JSON record of suite wall time "
     "vs. the tier-1 budget to this file (always printed in the terminal "
     "summary).")

# -- probe / diagnosis tools ------------------------------------------------
_var("MXTPU_PROBE_BATCH", "int", 256,
     "tools/mfu_probe.py, conv_probe.py, int8_probe.py, bn_bisect.py "
     "measurement batch size.")
_var("MXTPU_PROBE_ITERS", "int", None,
     "probe-tool measured iterations (tool-specific defaults: mfu 10, "
     "bn_bisect 20, int8 200, conv 400).")
_var("MXTPU_DIAG_TIMEOUT_S", "int", 60,
     "tools/diagnose.py accelerator-dial probe timeout.")
_var("MXTPU_PROBE_TIMEOUT", "int", 120,
     "tools/bench_capture.sh: per-attempt accelerator-dial probe timeout "
     "(seconds).")
_var("MXTPU_PROBE_INTERVAL", "int", 60,
     "tools/bench_capture.sh: initial sleep between accelerator probes "
     "(doubles up to `MXTPU_PROBE_INTERVAL_MAX`).")
_var("MXTPU_PROBE_DEADLINE", "int", 1800,
     "tools/bench_capture.sh accelerator-probe loop: total wall-clock "
     "budget before writing a stale-labeled `BENCH_<tag>_stale.json` and "
     "exiting.")
_var("MXTPU_PROBE_INTERVAL_MAX", "int", 300,
     "cap on the bench_capture probe loop's doubling backoff (seconds).")

# -- distributed: rendezvous + launcher -------------------------------------
_var("MXTPU_COORDINATOR", "str", None,
     "multi-process rendezvous coordinator address, emitted by "
     "`tools/launch.py` and consumed by `parallel.collectives."
     "init_process_group`.")
_var("MXTPU_NUM_WORKERS", "int", None,
     "process-group size for the rendezvous protocol (alias: "
     "`DMLC_NUM_WORKER`).")
_var("MXTPU_PROCESS_ID", "int", None,
     "this process's rank in the rendezvous protocol (alias: "
     "`DMLC_WORKER_ID`).")
_var("MXTPU_RENDEZVOUS_TIMEOUT", "int", 300,
     "seconds `init_process_group` / `kv.create('dist_sync')` waits for "
     "the group to assemble before raising a diagnosable `MXNetError` "
     "(instead of hanging on a peer that never arrives — "
     "docs/fault_tolerance.md §2).")
_var("MXTPU_RENDEZVOUS_RETRIES", "int", 0,
     "redial count (exponential backoff) for *transient* rendezvous "
     "errors; deadline expiries are not retried.")
_var("MXTPU_RESTART_GENERATION", "int", 0,
     "set by the `tools/launch.py --max-restarts` supervisor: which "
     "respawn generation this worker belongs to (`parallel.resilience."
     "restart_generation()`; fault injection defaults to generation 0 "
     "only).")
_var("MXTPU_TEARDOWN_GRACE", "float", 10.0,
     "launcher escalation window: seconds between group SIGTERM and "
     "SIGKILL on first failure.")
_var("MXTPU_CPU_COLLECTIVES", "str", "gloo",
     "cross-process collectives implementation selected when the platform "
     "is explicitly CPU (multi-process CPU groups need one; `none` "
     "disables).")

# -- resilience -------------------------------------------------------------
_var("MXTPU_FAULT_INJECT", "str", None,
     "deterministic fault injection at the trainer step boundary, e.g. "
     "`kill@step=7,rank=1`, `exc@step=3`, `hang@step=5,rank=1` (park the "
     "rank forever — watchdog/flight-recorder test vector), "
     "`corrupt_ckpt@step=5,dir=/ckpts`, `preempt@step=7,rank=1,grace=30` "
     "(SIGTERM-with-grace — the cloud preemption notice), "
     "`kill_during_ckpt@step=4,rank=0` (die mid-save, pre-publish — the "
     "torn-write window) (docs/fault_tolerance.md §5).")
_var("MXTPU_CKPT_DIR", "str", None,
     "default checkpoint directory for the `corrupt_ckpt` injection "
     "action (tests' resilience workers also read it).")
_var("MXTPU_CKPT_ASYNC", "bool", True,
     "route `CheckpointManager.save_async`/`save_sharded_async` through "
     "the named background writer thread (`mxtpu-ckpt-writer`): the "
     "training thread pays only the host snapshot, serialize+fsync+"
     "atomic-rename happen off-thread (at-most-one in flight, honest "
     "backpressure). `0` degrades both to the synchronous save path — "
     "the escape hatch when the extra host copy is the scarcer resource "
     "(docs/fault_tolerance.md §Preemption & elastic resume).")
_var("MXTPU_CKPT_SHARD_TIMEOUT_S", "float", 120.0,
     "sharded checkpoints: how long rank 0 waits for every peer rank's "
     "staged shard before abandoning the manifest publish (the staging "
     "dir stays invisible to `latest()`, so a peer death mid-save can "
     "never tear a checkpoint).")
_var("MXTPU_PREEMPT_GRACE_S", "float", 15.0,
     "graceful-preemption budget: seconds between the SIGTERM notice and "
     "the expected SIGKILL. `maybe_preempt_exit` finishes the in-flight "
     "step and emergency-checkpoints inside this window; a fault entry's "
     "`grace=` or `install_preemption_handler(grace_s=)` overrides it.")
_var("MXTPU_PREEMPT_EXIT_CODE", "int", 83,
     "rc a gracefully-preempted worker exits with after its emergency "
     "checkpoint. `tools/launch.py` treats a generation where any rank "
     "exited with this rc as a preemption: free restart (no "
     "`--max-restarts` budget consumed) and backoff reset. rc+1 (84) "
     "means preempted WITHOUT a checkpoint — budget-consuming.")

# -- serving ----------------------------------------------------------------
_var("MXTPU_SERVE_MAX_BATCH", "int", 32,
     "serving (`mxnet_tpu.serving`): maximum examples coalesced into one "
     "inference batch; also the terminal padding bucket (buckets are the "
     "powers of two up to this value — docs/serving.md).")
_var("MXTPU_SERVE_MAX_DELAY_MS", "float", 5.0,
     "serving: longest the micro-batcher holds an admitted request open "
     "waiting for coalescing partners before dispatching a partial batch.")
_var("MXTPU_SERVE_QUEUE_DEPTH", "int", 256,
     "serving admission control: bounded per-model request queue; a "
     "submit beyond this depth is rejected immediately (HTTP 429).")
_var("MXTPU_SERVE_TIMEOUT_MS", "float", 2000.0,
     "serving: default per-request deadline (queue wait + compute); an "
     "expired request is dropped and answered HTTP 504. A request body "
     "may override it via its `timeout_ms` field.")
_var("MXTPU_SERVE_PORT", "int", 8500,
     "serving: default HTTP port for `tools/serve.py` / `ServingServer` "
     "(0 binds a free port — tests and serve_bench).")
_var("MXTPU_SERVE_DRAIN_TIMEOUT_MS", "float", 30000.0,
     "serving: graceful-shutdown budget in ms — how long SIGTERM/`/drainz` "
     "waits for queued + in-flight requests to finish. A wedged executor "
     "must not wedge shutdown forever: on expiry the drain FORCE-completes "
     "every stranded request with a deterministic 503 and the process "
     "exits nonzero (docs/serving.md drain semantics; replaced the "
     "seconds-typed `MXTPU_SERVE_DRAIN_TIMEOUT_S`).")
_var("MXTPU_SERVE_DRAIN_TIMEOUT_S", "float", None,
     "DEPRECATED serving drain budget (seconds-typed predecessor of "
     "`MXTPU_SERVE_DRAIN_TIMEOUT_MS`). Still honored — with a startup "
     "warning — when set and the `_MS` name is not, so existing "
     "deployments' drain settings survive the rename.")
_var("MXTPU_SERVE_REPLICAS", "int", 0,
     "serving: replica worker processes per served model (`tools/serve.py "
     "--replicas`). 0 runs the model in-process (no pool); N >= 1 runs N "
     "supervised replica processes with health-checked failover "
     "(docs/serving.md resilience).")
_var("MXTPU_SERVE_HEARTBEAT_MS", "float", 1000.0,
     "serving replica pool: health-check heartbeat deadline. An idle "
     "replica that misses a ping/pong round trip by this much — or a busy "
     "one silent past its batch deadline plus this grace — is declared "
     "wedged, ejected (process-group teardown) and respawned.")
_var("MXTPU_SERVE_WEDGE_TIMEOUT_MS", "float", 10000.0,
     "serving replica pool: compute-budget FLOOR for busy-replica wedge "
     "detection. A busy replica is ejected only after staying silent past "
     "max(batch deadline budget, this floor) plus the heartbeat grace — "
     "decoupling wedge detection from client deadlines so a model whose "
     "forward legitimately outlasts a request budget is not SIGKILLed "
     "mid-compute (deadline-less batches use the floor alone).")
_var("MXTPU_SERVE_POOL_TOKEN", "str", None,
     "serving replica pool: INTERNAL per-pool handshake secret. Set by "
     "the pool in each replica worker's environment; a connecting worker "
     "must present it before any pickled frame is read, so another local "
     "user cannot reach the router's unpickler or hijack a replica slot. "
     "Not meant to be set by operators.")
_var("MXTPU_SERVE_RESTART_BACKOFF_MS", "float", 200.0,
     "serving replica pool: initial delay before respawning an ejected "
     "replica (doubles per consecutive restart of the same replica, "
     "capped at 60s; resets once a generation serves a batch cleanly).")
_var("MXTPU_SERVE_KV_PAGES", "int", 256,
     "generation serving (`mxnet_tpu.serving.generate`): total fixed-size "
     "KV-cache pages allocated per served LM. The whole pool is allocated "
     "at load (its bytes are part of the model footprint the "
     "`MXTPU_SERVE_MEMORY_BUDGET` admission check prices — a 507 at load "
     "time instead of an OOM mid-decode); the free-list allocator hands "
     "pages to sequences at admission and reclaims them at completion "
     "(`mxtpu_serve_kv_pages_{total,used}`).")
_var("MXTPU_SERVE_KV_PAGE_SIZE", "int", 16,
     "generation serving: tokens per KV-cache page. Smaller pages waste "
     "less on short tails but grow the per-sequence page table (and the "
     "decode executable's gather width); 16 matches the classic "
     "PagedAttention block size.")
_var("MXTPU_SERVE_MAX_NEW_TOKENS", "int", 128,
     "generation serving: cap on a request's `max_new_tokens` (also the "
     "per-request default when the body omits it). Together with "
     "`MXTPU_SERVE_MAX_PROMPT` it bounds the pages a sequence can ever "
     "need, so admission reserves worst-case pages up front and a "
     "running batch can never deadlock on the page pool.")
_var("MXTPU_SERVE_MAX_PROMPT", "int", 64,
     "generation serving: longest admissible prompt in tokens. Prompts "
     "pad to power-of-two prefill buckets up to this length — one cached "
     "prefill executable per bucket, so steady-state admission never "
     "compiles.")

# -- elastic autoscaling (docs/serving.md §Autoscaling) ---------------------
_var("MXTPU_AUTOSCALE", "bool", False,
     "arm the elastic autoscaler in `tools/serve.py`: one named "
     "controller thread per server (`serving.Autoscaler`) that consumes "
     "`slo.verdicts()` and resizes replica pools in place — scale up on "
     "sustained SLO breach (admitted against `MXTPU_SERVE_MEMORY_BUDGET` "
     "headroom, warm via manifest prefetch), scale down + drain on idle. "
     "Library callers construct `Autoscaler` directly; this gate is the "
     "launcher's.")
_var("MXTPU_AUTOSCALE_INTERVAL_MS", "float", 1000.0,
     "autoscaler evaluation-lap period. Each lap reads the current SLO "
     "verdicts and takes at most one scaling action per model.")
_var("MXTPU_AUTOSCALE_UP_WINDOWS", "int", 2,
     "consecutive breached evaluation laps (any paging SLO objective "
     "scoped to the model) before a scale-up — the fast-side hysteresis: "
     "one noisy window never adds a replica.")
_var("MXTPU_AUTOSCALE_IDLE_S", "float", 60.0,
     "sustained idle (seconds since the model's request counters last "
     "moved — the windowed staleness view) before the autoscaler drains "
     "one replica away, never below the model's `min_replicas`. Also the "
     "\"cold\" threshold budget-pressure shrinking uses.")
_var("MXTPU_AUTOSCALE_COOLDOWN_S", "float", 5.0,
     "minimum seconds between two scaling actions on one model (up or "
     "down), so a decision's effect — a warming replica, a drained one — "
     "lands in the windows before the next decision reads them.")
_var("MXTPU_AUTOSCALE_MIN_REPLICAS", "int", 1,
     "default per-model replica floor for scale-down and budget-pressure "
     "shrinking (`ModelRepository.load(min_replicas=)` overrides per "
     "model).")
_var("MXTPU_AUTOSCALE_MAX_REPLICAS", "int", 8,
     "default per-model replica ceiling for scale-up "
     "(`ModelRepository.load(max_replicas=)` overrides per model); a "
     "breach at the ceiling records an `autoscale_blocked` decision "
     "instead of growing.")
_var("MXTPU_AUTOSCALE_EVICT_TTL_S", "float", 300.0,
     "budget-pressure eviction TTL: a model idle longer than this (and "
     "not `pinned`) may be UNLOADED by `ModelRepository.reclaim_memory` "
     "when a new load or scale-up needs headroom — coldest first, after "
     "shrinking pooled models toward their floors. Its persisted warmup "
     "manifest makes a later reload warm in seconds.")

# -- accelerator dial -------------------------------------------------------
_var("MXTPU_DIAL_TIMEOUT_S", "float", 60.0,
     "`runtime.dial_devices`: seconds the PJRT device dial (`jax."
     "devices()`) may block before the deadline probe raises a diagnosable "
     "MXNetError (a wedged axon tunnel otherwise blocks forever — the "
     "ROADMAP item-5 failure class). Flight-recorder events bracket every "
     "dial.")
_var("MXTPU_TOPOLOGY_CACHE", "str", None,
     "path of the device-topology cache file `runtime.dial_devices` "
     "writes after a successful non-CPU dial (platform/device kind/count/"
     "timestamp JSON). A later failed dial reports the last known "
     "topology instead of nothing; `tools/bench_capture.sh` arms it so "
     "stale artifacts are labeled with the hardware they missed.")

# -- telemetry / flight recorder --------------------------------------------
_var("MXTPU_TELEMETRY", "bool", True,
     "master switch for the always-on metrics/flight-recorder layer "
     "(docs/observability.md); `0` turns every counter/event into a "
     "no-op.")
_var("MXTPU_TELEMETRY_DIR", "str", None,
     "directory for telemetry output: periodic per-process "
     "`telemetry-rank<R>-pid<P>.jsonl` snapshots, `launcher-events.jsonl` "
     "(tools/launch.py supervision events) and `flightrec-*.json` hang "
     "dumps. Also arms the import-time SIGUSR1 dump handler. Read once at "
     "first use — set before the process starts recording.")
_var("MXTPU_TELEMETRY_FLUSH_S", "float", 10.0,
     "period of the JSONL flusher thread (a final flush always runs at "
     "exit).")
_var("MXTPU_TELEMETRY_PORT", "int", None,
     "base port for the Prometheus text-exposition endpoint; each rank "
     "serves `/metrics` on `port + rank` (stdlib http.server; default off "
     "— metrics-on/endpoint-off posture).")
_var("MXTPU_WATCHDOG_TIMEOUT", "float", None,
     "hang watchdog: seconds without a completed training step (armed by "
     "the FIRST completed step, so initial compile never trips it) before "
     "the flight recorder dumps all-thread stacks + recent events.")
_var("MXTPU_WATCHDOG_ACTION", "str", "abort",
     "what follows a watchdog dump: `abort` exits the process (code "
     "`MXTPU_WATCHDOG_EXIT_CODE`, 43) so the launcher tears down/restarts "
     "the group; `dump` keeps the process alive and re-arms.")
_var("MXTPU_WATCHDOG_EXIT_CODE", "int", 43,
     "exit status of a watchdog abort (distinct from the fault-injection "
     "code 42).")
_var("MXTPU_FLIGHTREC_EVENTS", "int", 512,
     "flight-recorder ring size (recent telemetry events kept per process "
     "for dumps).")
_var("MXTPU_DUMP_GRACE", "float", 1.0,
     "launcher teardown: seconds between the SIGUSR1 (flight-recorder "
     "dump) broadcast and SIGTERM. The broadcast only happens when "
     "`MXTPU_TELEMETRY_DIR` is set (the same condition that installs the "
     "worker-side dump handler at import); otherwise teardown starts "
     "directly at SIGTERM.")
_var("MXTPU_MEMORY_POLL_MS", "float", None,
     "period of the background memory-gauge poller "
     "(`telemetry.memory.sample`: device `memory_stats()`, process "
     "RSS/VmHWM, NDArray live bytes). Default off — gauges still refresh "
     "at every JSONL flush, Prometheus scrape and training step; the "
     "poller is for catching spikes inside long forwards between steps.")
_var("MXTPU_SERVE_MEMORY_BUDGET", "str", None,
     "serving memory budget in bytes (suffixes K/M/G/T accepted, e.g. "
     "`24G`): `ModelRepository.load` computes each model's device "
     "footprint from per-executable `memory_analysis()` figures "
     "(docs/observability.md §Memory) and REJECTS a load whose footprint "
     "would exceed the budget (typed `MemoryBudgetError`). A `warn:` "
     "prefix (e.g. `warn:24G`) logs + emits an event instead of "
     "rejecting. Unset (default) disables the check; loads whose "
     "footprint is unknown (no figures recorded) are never rejected.")
_var("MXTPU_STEP_FLOPS", "float", None,
     "model FLOPs per training step; when set, `observe_step` publishes "
     "achieved MFU (`mxtpu_step_mfu`) against `runtime.chip_peak_tflops` "
     "× local device count (API spelling: `telemetry.set_step_flops`). "
     "Overrides the automatic cost-analysis accounting "
     "(`MXTPU_TRACE_FLOPS`).")
_var("MXTPU_GOODPUT", "bool", True,
     "per-step goodput attribution (docs/observability.md §Goodput): "
     "every training step decomposes into exhaustive, non-overlapping "
     "phases (`data_wait`/`host_dispatch`/`compile`/`compute`/"
     "`checkpoint_stall`/`collective`/`other`) published as "
     "`mxtpu_step_phase_seconds{phase=}` plus the rolling "
     "`mxtpu_goodput_fraction` gauge. `0` turns the accountant into a "
     "no-op (the legacy `module.fit` data-wait split keeps working).")
_var("MXTPU_GOODPUT_WINDOW_STEPS", "int", 128,
     "steps in the rolling window behind `mxtpu_goodput_fraction` and the "
     "`/statusz` `training` block (windowed compute ÷ wall, top stall "
     "phase).")

# -- SLO engine -------------------------------------------------------------
_var("MXTPU_SLO", "bool", True,
     "master switch for the SLO engine (docs/observability.md §SLOs): "
     "objective registration, the burn-rate evaluator thread and the "
     "`mxtpu_slo_*` gauges. `0` disables everything except the raw "
     "windowed-view machinery (rings still roll on the flusher cadence).")
_var("MXTPU_SLO_SPEC", "str", None,
     "path of a JSON SLO spec file (`{\"objectives\": [...]}`); objectives "
     "declared there are registered next to the built-in serving/training "
     "ones at evaluator start. Malformed JSON, an unknown objective kind "
     "or an unknown metric name raise a typed `SLOSpecError` EAGERLY — a "
     "typo'd objective silently never evaluating would be an alert that "
     "can never fire.")
_var("MXTPU_SLO_WINDOW_MS", "float", 5000.0,
     "resolution of the windowed-telemetry snapshot rings: how often "
     "`roll_windows` appends one per-metric snapshot (rolled on the JSONL "
     "flusher cadence and each SLO evaluator lap, throttled to this "
     "period). Windowed `rate(60s)` / `quantile(0.99, 60s)` views diff "
     "the live value against the ring.")
_var("MXTPU_SLO_EVAL_MS", "float", None,
     "period of the SLO evaluator thread's laps (compute burn rates, "
     "publish `mxtpu_slo_*` gauges, emit breach/recovery events). Default: "
     "the `MXTPU_SLO_WINDOW_MS` resolution.")
_var("MXTPU_SLO_FAST_WINDOWS", "str", "60,300",
     "comma-separated fast (page-level) burn-rate windows in seconds, "
     "SRE-style: an objective pages only when EVERY fast window is "
     "burning (the short window proves it is happening now, the long one "
     "that it is not a blip).")
_var("MXTPU_SLO_SLOW_WINDOW_S", "float", 1800.0,
     "slow (ticket-level) burn-rate window in seconds; also sizes the "
     "snapshot rings (ring length = slow window / resolution, capped at "
     "4096 entries).")
_var("MXTPU_SLO_BURN_PAGE", "float", 1.0,
     "fast-window burn-rate threshold for the page-level (breaching) "
     "verdict: 1.0 pages as soon as the objective is violated at a "
     "budget-consuming rate across every fast window; raise it to page "
     "only on faster budget burn.")
_var("MXTPU_SLO_BURN_TICKET", "float", 1.0,
     "slow-window burn-rate threshold for the ticket-level verdict.")
_var("MXTPU_SLO_ALERTS", "int", 64,
     "size of the bounded alerts ring (last `slo_breach`/`slo_recovered` "
     "transitions) carried in flight-recorder dumps and `/statusz` — a "
     "watchdog/SIGUSR1 dump names which objective was burning when the "
     "process hung.")
_var("MXTPU_SLO_SERVE_P99_MS", "float", 1000.0,
     "built-in serving latency objective: p99 of "
     "`mxtpu_serve_request_seconds` (admission to resolution, per model) "
     "must stay under this many ms. Registered for every served model at "
     "load.")
_var("MXTPU_SLO_SERVE_AVAILABILITY", "float", 0.999,
     "built-in serving availability objective: the fraction of requests "
     "NOT deterministically rejected (429/504/503 sheds) must stay at or "
     "above this target; the error budget is `1 - target`.")
_var("MXTPU_SLO_SERVE_QUEUE_FRAC", "float", 0.8,
     "built-in serving queue-depth ceiling: `mxtpu_serve_queue_depth` "
     "must stay under this fraction of `MXTPU_SERVE_QUEUE_DEPTH` — the "
     "queue sitting near its admission limit is the page BEFORE 429s "
     "start (and the ROADMAP item-4 autoscaler's scale-up signal).")
_var("MXTPU_SLO_INTERTOKEN_P99_MS", "float", 250.0,
     "built-in generation objective: p99 of "
     "`mxtpu_serve_intertoken_seconds` (what a streaming client feels) "
     "must stay under this many ms.")
_var("MXTPU_SLO_KV_OCCUPANCY", "float", 0.95,
     "built-in generation objective: `mxtpu_serve_kv_occupancy` (used/"
     "total KV pages) ceiling — occupancy pinned above it means "
     "admissions are about to queue on page pressure.")
_var("MXTPU_SLO_STEP_SECONDS", "float", None,
     "optional training objective (registered at the first `observe_step` "
     "when set): p99 step time in seconds per trainer kind — a fleet's "
     "step-time regression page.")
_var("MXTPU_SLO_MFU_FLOOR", "float", None,
     "optional training objective (registered at the first `observe_step` "
     "when set): `mxtpu_step_mfu` floor, 0..1 — pages when achieved MFU "
     "drops below it (input starvation, a de-optimized step, a sick "
     "chip).")
_var("MXTPU_SLO_GOODPUT_FLOOR", "float", None,
     "optional training objective (registered at the first `observe_step` "
     "when set): `mxtpu_goodput_fraction` floor, 0..1 — pages when the "
     "windowed compute ÷ wall fraction drops below it (input stalls, "
     "checkpoint stalls, recompile storms; docs/observability.md "
     "§Goodput).")
_var("MXTPU_SLO_STEP_STALENESS_S", "float", None,
     "optional training staleness objective (registered at the first "
     "`observe_step` when set): seconds `mxtpu_steps_total` may sit "
     "without advancing before the objective burns — the SLO-shaped "
     "cousin of the flight-recorder watchdog.")

# -- distributed tracing ----------------------------------------------------
_var("MXTPU_TRACE_SAMPLE", "float", 0.0,
     "distributed tracing (docs/observability.md §Tracing): fraction of "
     "new root traces (serving requests, training steps) that record "
     "spans, 0.0..1.0. Default 0 — spans cost nothing unless sampled in; "
     "an incoming `x-mxtpu-trace` header / wire context with the sampled "
     "flag is always honored regardless of the local rate.")
_var("MXTPU_TRACE_SLOW_MS", "float", None,
     "always-sample-on-slow escape hatch: when set, unsampled root spans "
     "are buffered locally and RETROACTIVELY emitted if the root runs "
     "longer than this many milliseconds — every slow request/step leaves "
     "a trace even at sample rate 0. (Local-process spans only: a child "
     "process cannot know the root overran.)")
_var("MXTPU_TRACE_CONTEXT", "str", None,
     "inherited trace context, `<trace_id>-<span_id>-<flags>` (the "
     "`x-mxtpu-trace` header format). Set by `tools/launch.py` for each "
     "worker so training-step root spans join the launch's generation "
     "span; honored as the ambient parent for root spans minted in this "
     "process.")
_var("MXTPU_TRACE_FLOPS", "bool", True,
     "automatic FLOP accounting: derive per-executable FLOPs from JAX's "
     "lowered-HLO cost analysis at the unified executable registry's "
     "fill hook (`mxnet_tpu.compile` — eager ops, autograd backward, "
     "Executor builds, CachedOp, serving bucket warm) and "
     "accumulate executed FLOPs so `observe_step` publishes MFU with no "
     "manual `set_step_flops`. `0` disables the accounting (and the "
     "per-shape lowering it pays on each cache fill).")


# ---------------------------------------------------------------------------
# docs generation
# ---------------------------------------------------------------------------

def markdown_table():
    """The docs/env_vars.md Framework table, generated from the registry
    (one row per variable, declaration order). The env-registry lint
    checker proves the committed table matches this registry."""
    lines = ["| Variable | Default | Effect |", "|---|---|---|"]
    for var in _REGISTRY.values():
        doc = " ".join(var.doc.split())
        lines.append("| `%s` | %s | %s |" % (var.name, var.default_str(),
                                             doc))
    return "\n".join(lines) + "\n"


if __name__ == "__main__":
    import sys

    args = sys.argv[1:]
    if args in ([], ["--markdown"]):
        sys.stdout.write(markdown_table())
    elif args == ["--names"]:
        sys.stdout.write("\n".join(names()) + "\n")
    else:
        sys.stderr.write("usage: python -m mxnet_tpu.env "
                         "[--markdown | --names]\n")
        sys.exit(2)
