"""Base utilities: errors, registries, dtype handling.

TPU-native rebuild of the reference's `python/mxnet/base.py` role (ctypes
plumbing, error translation — reference: python/mxnet/base.py). Here there is
no C ABI to cross for the frontend: the "backend" is JAX/XLA, so this module
only carries the shared error type, the string/dtype conversion helpers, and
the small registry machinery the op/optimizer/metric/initializer registries use
(reference: python/mxnet/registry.py).
"""
from __future__ import annotations

import os
import tempfile

import numpy as _np

__all__ = ["MXNetError", "string_types", "numeric_types", "integer_types",
           "atomic_writer", "unpad_outputs"]

# Host-array mode: when True, host-side pipeline stages (image decode,
# dataset __getitem__) hand back plain numpy instead of NDArray. Set in
# DataLoader worker processes, where touching the (forked) jax runtime
# deadlocks and where the TPU tunnel must never be dialed. See
# gluon/data/dataloader.py.
HOST_ARRAY_MODE = False


def honor_explicit_cpu_platform():
    """Re-assert an EXPLICIT ``JAX_PLATFORMS=cpu`` env choice over a
    sitecustomize PJRT hook that force-overrides ``jax_platforms`` at
    interpreter start (dialing accelerator hardware — a wedged remote dial
    then hangs the first jax computation). Only the exact value "cpu" is
    honored: accelerator selections keep whatever fallback chain (e.g.
    "axon,cpu") the deployment configured. Called from package import and
    from the embedded-interpreter C bridge; safe to call repeatedly."""
    import os

    if os.environ.get("JAX_PLATFORMS") != "cpu":
        return
    try:
        import jax

        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — never block import on config shape
        pass


def enable_persistent_compile_cache():
    """Opt-in *jax-level* persistent compilation cache: set
    ``MXTPU_JAX_COMPILE_CACHE`` to a directory (or ``1`` for the repo-local
    default) and jax caches executables keyed by HLO+backend, so repeated
    runs skip XLA backend compilation (each process still pays
    trace+lower). This is the optional extra knob UNDER the framework's own
    persistent executable-artifact tier (``MXTPU_COMPILE_CACHE`` →
    `mxnet_tpu.compile`, docs/compile_cache.md), which skips trace, lower
    AND compile; the two compose. Deliberately NOT default-on: XLA:CPU
    AOT reloads warn about machine-feature mismatches (potential SIGILL) and
    save little, so the CPU test suite stays uncached; ``bench.py`` arms
    both for accelerator runs. Best-effort: backends that cannot serialize
    executables simply miss the cache."""
    import os

    from . import env as _env

    choice = _env.raw("MXTPU_JAX_COMPILE_CACHE") or ""
    if not choice or choice.lower() in ("0", "off", "none", "disable",
                                        "false", "no"):
        return
    if choice.lower() in ("1", "on", "true", "yes"):
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache")
    else:
        cache_dir = choice
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast compiles: over a tunneled dial the round-trip,
        # not local compile time, is what repeat runs are paying for
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:  # noqa: BLE001 — never block import on config shape
        pass


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: python/mxnet/base.py:49)."""


class atomic_writer:
    """Crash-consistent file write: ``with atomic_writer(path, 'wb') as f``
    writes to a same-directory temp file, fsyncs it, and atomically renames
    onto `path` only if the block completed — a process killed mid-write can
    leave a stale temp file but never a truncated `path`. Readers therefore
    always see either the previous complete file or the new complete file
    (the reference's single-file NDArray::Save had no such guarantee; a kill
    mid-save corrupted the checkpoint). The rename is same-filesystem by
    construction (temp lives next to the target)."""

    def __init__(self, path, mode="wb"):
        self._path = os.fspath(path)
        self._mode = mode
        self._tmp = None
        self._f = None

    def __enter__(self):
        d = os.path.dirname(os.path.abspath(self._path)) or "."
        fd, self._tmp = tempfile.mkstemp(
            dir=d, prefix=os.path.basename(self._path) + ".tmp-")
        # mkstemp creates 0600; the rename would stamp that onto the target.
        # Preserve an existing target's mode, else honor the umask like a
        # plain open() would — shared-directory checkpoints must stay
        # readable by their consumers (eval/monitoring processes).
        try:
            mode = os.stat(self._path).st_mode & 0o7777
        except OSError:
            umask = os.umask(0)
            os.umask(umask)
            mode = 0o666 & ~umask
        try:
            os.fchmod(fd, mode)
        except OSError:
            pass
        self._f = os.fdopen(fd, self._mode)
        return self._f

    def __exit__(self, exc_type, exc, tb):
        try:
            try:
                if exc_type is None:
                    self._f.flush()
                    os.fsync(self._f.fileno())
            finally:
                # close unconditionally — a flush/fsync failure (ENOSPC)
                # must not leak the temp fd on every retried checkpoint
                self._f.close()
            if exc_type is None:
                os.replace(self._tmp, self._path)
                self._tmp = None
                _fsync_dir(os.path.dirname(os.path.abspath(self._path)) or ".")
        finally:
            if self._tmp is not None and os.path.exists(self._tmp):
                os.unlink(self._tmp)
        return False


def _fsync_dir(path):
    """Persist a rename by fsyncing the containing directory (POSIX: the
    rename itself is atomic but only durable once the dir entry is synced).
    Best-effort — some filesystems refuse O_RDONLY dir fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def unpad_outputs(outputs, pad, copy=False):
    """Drop the trailing ``pad`` rows from every array in ``outputs``.

    The shared unpad for every padded-batch consumer: a DataIter's last
    batch carries ``pad`` filler rows (module predict/iter_predict), and the
    serving micro-batcher pads coalesced batches up to a power-of-two bucket
    (serving/batcher.py). Works on anything row-sliceable (NDArray, numpy).
    ``copy=True`` detaches each slice from the padded buffer (callers that
    retain results past the next forward need it).
    """
    out = []
    for o in outputs:
        s = o[0:o.shape[0] - pad] if pad else o
        out.append(s.copy() if copy else s)
    return out


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# dtype name <-> numpy dtype mapping (reference keeps int codes in
# python/mxnet/base.py via _DTYPE_NP_TO_MX; we key on names since XLA is typed)
_DTYPE_ALIASES = {
    "float32": _np.float32,
    "float64": _np.float64,
    "float16": _np.float16,
    "bfloat16": "bfloat16",  # resolved lazily via ml_dtypes through jax.numpy
    "uint8": _np.uint8,
    "int8": _np.int8,
    "int32": _np.int32,
    "int64": _np.int64,
    "bool": _np.bool_,
}


def np_dtype(dtype):
    """Normalize a user-provided dtype (string/np.dtype/jnp dtype) to numpy dtype."""
    import jax.numpy as jnp

    if dtype is None:
        return _np.dtype(_np.float32)
    if isinstance(dtype, str) and dtype == "bfloat16":
        return _np.dtype(jnp.bfloat16)
    return _np.dtype(dtype)


_ALL_REGISTRIES = {}


class _Registry:
    """Simple name->object registry with alias support
    (reference: python/mxnet/registry.py:30 `get_register_func`)."""

    def __init__(self, kind):
        self.kind = kind
        self._map = {}
        # kind-keyed directory so mx.registry's functional surface
        # (registry.py) resolves onto the SAME storage as the subsystem
        # registries (optimizer/metric/initializer) — first instance wins
        _ALL_REGISTRIES.setdefault(kind, self)

    def register(self, obj, name=None, aliases=()):
        key = (name or getattr(obj, "__name__", str(obj))).lower()
        self._map[key] = obj
        for a in aliases:
            self._map[a.lower()] = obj
        return obj

    def get(self, name):
        key = name.lower()
        if key not in self._map:
            raise MXNetError(
                "Cannot find %s '%s'. Valid: %s"
                % (self.kind, name, sorted(self._map))
            )
        return self._map[key]

    def create(self, name, *args, **kwargs):
        return self.get(name)(*args, **kwargs)

    def __contains__(self, name):
        return name.lower() in self._map

    def keys(self):
        return list(self._map)

def device_int_dtype():
    """The documented int64 policy (README "int64") in one place: device
    index/shape integers are int32 (XLA-native) under the default config,
    int64 when large-tensor mode has scoped x64 live
    (ndarray._x64_if_large)."""
    import jax
    import jax.numpy as jnp

    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
