"""Base utilities: errors, registries, dtype handling.

TPU-native rebuild of the reference's `python/mxnet/base.py` role (ctypes
plumbing, error translation — reference: python/mxnet/base.py). Here there is
no C ABI to cross for the frontend: the "backend" is JAX/XLA, so this module
only carries the shared error type, the string/dtype conversion helpers, and
the small registry machinery the op/optimizer/metric/initializer registries use
(reference: python/mxnet/registry.py).
"""
from __future__ import annotations

import numpy as _np

__all__ = ["MXNetError", "string_types", "numeric_types", "integer_types"]

# Host-array mode: when True, host-side pipeline stages (image decode,
# dataset __getitem__) hand back plain numpy instead of NDArray. Set in
# DataLoader worker processes, where touching the (forked) jax runtime
# deadlocks and where the TPU tunnel must never be dialed. See
# gluon/data/dataloader.py.
HOST_ARRAY_MODE = False


def honor_explicit_cpu_platform():
    """Re-assert an EXPLICIT ``JAX_PLATFORMS=cpu`` env choice over a
    sitecustomize PJRT hook that force-overrides ``jax_platforms`` at
    interpreter start (dialing accelerator hardware — a wedged remote dial
    then hangs the first jax computation). Only the exact value "cpu" is
    honored: accelerator selections keep whatever fallback chain (e.g.
    "axon,cpu") the deployment configured. Called from package import and
    from the embedded-interpreter C bridge; safe to call repeatedly."""
    import os

    if os.environ.get("JAX_PLATFORMS") != "cpu":
        return
    try:
        import jax

        if jax.config.jax_platforms != "cpu":
            jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — never block import on config shape
        pass


def enable_persistent_compile_cache():
    """Opt-in persistent XLA compilation cache: set ``MXTPU_COMPILE_CACHE``
    to a directory (or ``1`` for the repo-local default) and executables are
    cached keyed by HLO+backend, so repeated bench/capture runs — each a
    fresh process compiling the same ResNet/BERT step over a slow remote
    dial — skip straight to execution. Deliberately NOT default-on: XLA:CPU
    AOT reloads warn about machine-feature mismatches (potential SIGILL) and
    save little, so the CPU test suite stays uncached; ``bench.py`` arms it
    for accelerator runs. Best-effort: backends that cannot serialize
    executables simply miss the cache."""
    import os

    choice = os.environ.get("MXTPU_COMPILE_CACHE", "")
    if not choice or choice.lower() in ("0", "off", "none", "disable",
                                        "false", "no"):
        return
    if choice.lower() in ("1", "on", "true", "yes"):
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache")
    else:
        cache_dir = choice
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast compiles: over a tunneled dial the round-trip,
        # not local compile time, is what repeat runs are paying for
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:  # noqa: BLE001 — never block import on config shape
        pass


class MXNetError(RuntimeError):
    """Error raised by the framework (reference: python/mxnet/base.py:49)."""


string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)

# dtype name <-> numpy dtype mapping (reference keeps int codes in
# python/mxnet/base.py via _DTYPE_NP_TO_MX; we key on names since XLA is typed)
_DTYPE_ALIASES = {
    "float32": _np.float32,
    "float64": _np.float64,
    "float16": _np.float16,
    "bfloat16": "bfloat16",  # resolved lazily via ml_dtypes through jax.numpy
    "uint8": _np.uint8,
    "int8": _np.int8,
    "int32": _np.int32,
    "int64": _np.int64,
    "bool": _np.bool_,
}


def np_dtype(dtype):
    """Normalize a user-provided dtype (string/np.dtype/jnp dtype) to numpy dtype."""
    import jax.numpy as jnp

    if dtype is None:
        return _np.dtype(_np.float32)
    if isinstance(dtype, str) and dtype == "bfloat16":
        return _np.dtype(jnp.bfloat16)
    return _np.dtype(dtype)


_ALL_REGISTRIES = {}


class _Registry:
    """Simple name->object registry with alias support
    (reference: python/mxnet/registry.py:30 `get_register_func`)."""

    def __init__(self, kind):
        self.kind = kind
        self._map = {}
        # kind-keyed directory so mx.registry's functional surface
        # (registry.py) resolves onto the SAME storage as the subsystem
        # registries (optimizer/metric/initializer) — first instance wins
        _ALL_REGISTRIES.setdefault(kind, self)

    def register(self, obj, name=None, aliases=()):
        key = (name or getattr(obj, "__name__", str(obj))).lower()
        self._map[key] = obj
        for a in aliases:
            self._map[a.lower()] = obj
        return obj

    def get(self, name):
        key = name.lower()
        if key not in self._map:
            raise MXNetError(
                "Cannot find %s '%s'. Valid: %s"
                % (self.kind, name, sorted(self._map))
            )
        return self._map[key]

    def create(self, name, *args, **kwargs):
        return self.get(name)(*args, **kwargs)

    def __contains__(self, name):
        return name.lower() in self._map

    def keys(self):
        return list(self._map)

def device_int_dtype():
    """The documented int64 policy (README "int64") in one place: device
    index/shape integers are int32 (XLA-native) under the default config,
    int64 when large-tensor mode has scoped x64 live
    (ndarray._x64_if_large)."""
    import jax
    import jax.numpy as jnp

    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
