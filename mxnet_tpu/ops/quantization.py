"""INT8 quantization ops.

TPU-native equivalent of the reference's quantization operator family
(src/operator/quantization/**: quantize_v2, dequantize, requantize,
quantized_fully_connected, quantized_conv — SURVEY §2.1 N10). The reference
dispatches to cuDNN/MKLDNN int8 kernels; here the int8 compute lowers to
XLA `dot_general`/`conv_general_dilated` with `preferred_element_type=int32`
— the MXU executes int8×int8→int32 natively.

Quantization scheme matches the reference's symmetric int8 path
(quantization_utils.h): scale = 127 / max(|min|, |max|), zero-point-free.
"""
from __future__ import annotations

from . import register

_INT8_MAX = 127.0


def _range_scale(min_range, max_range):
    import jax.numpy as jnp

    return _INT8_MAX / jnp.maximum(jnp.maximum(jnp.abs(min_range),
                                               jnp.abs(max_range)), 1e-20)


@register("_contrib_quantize_v2", num_outputs=3,
          aliases=("quantize_v2", "_contrib_quantize", "quantize"))
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """fp32 -> int8 + (min, max) range outputs (reference:
    quantize_v2-inl.h). Without calibrated ranges the data min/max is used
    (the reference's runtime-minmax mode)."""
    import jax.numpy as jnp

    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data).astype(jnp.float32)
        mx = jnp.max(data).astype(jnp.float32)
    else:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    scale = _range_scale(mn, mx)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    return q, mn.reshape((1,)), mx.reshape((1,))


def dequantize_int32(data, mn, mx):
    """Quantized accumulator -> fp32 given its range (shared body for the
    dequantize op and requantize)."""
    import jax.numpy as jnp

    scale = _range_scale(mn, mx)
    return data.astype(jnp.float32) / scale


@register("_contrib_dequantize", num_outputs=1, aliases=("dequantize",))
def dequantize(data, min_range, max_range, out_type="float32"):
    """int8/int32 -> fp32 (reference: dequantize-inl.h)."""
    return dequantize_int32(data, min_range.reshape(()), max_range.reshape(()))


@register("_contrib_requantize", num_outputs=3, aliases=("requantize",))
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 -> int8 with new ranges (reference: requantize-inl.h)."""
    import jax.numpy as jnp

    f = dequantize_int32(data, min_range.reshape(()), max_range.reshape(()))
    if min_calib_range is None:
        mn = jnp.min(f).astype(jnp.float32)
        mx = jnp.max(f).astype(jnp.float32)
    else:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    scale = _range_scale(mn, mx)
    q = jnp.clip(jnp.round(f * scale), -127, 127).astype(jnp.int8)
    return q, mn.reshape((1,)), mx.reshape((1,))


@register("_contrib_quantized_fully_connected", num_outputs=3,
          aliases=("quantized_fully_connected",))
def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias=None,
                              max_bias=None, num_hidden=0, no_bias=False,
                              flatten=True):
    """int8 FC: int8×int8 → int32 on the MXU (reference:
    quantized_fully_connected.cc). Inputs carry their fp ranges; output is
    the int32 accumulator + its implied range. `flatten` matches the fp32
    FullyConnected semantics (>2-D data collapses to (N, -1))."""
    import jax
    import jax.numpy as jnp

    if flatten and data.ndim > 2:
        data = data.reshape((data.shape[0], -1))
    acc = jax.lax.dot_general(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        (((data.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    sd = _range_scale(min_data.reshape(()), max_data.reshape(()))
    sw = _range_scale(min_weight.reshape(()), max_weight.reshape(()))
    out_scale = sd * sw
    if not no_bias and bias is not None:
        bq = jnp.round(bias * out_scale).astype(jnp.int32)
        acc = acc + bq
    # range chosen so dequantize(acc, -m, m) divides by exactly out_scale:
    # the int32 accumulator's value scale (reference carries ranges the
    # same way through quantized_* -> requantize/dequantize)
    out_max = _INT8_MAX / out_scale
    return acc, (-out_max).reshape((1,)), out_max.reshape((1,))


@register("_contrib_quantized_conv", num_outputs=3,
          aliases=("quantized_conv",))
def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias=None, max_bias=None, kernel=(),
                   stride=(1, 1), pad=(0, 0), num_filter=0, no_bias=False):
    """int8 NCHW convolution -> int32 accumulator (reference:
    quantized_conv.cc)."""
    import jax
    import jax.numpy as jnp

    stride = tuple(stride) or (1, 1)
    pad = tuple(pad) or (0, 0)
    acc = jax.lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=stride,
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.int32)
    sd = _range_scale(min_data.reshape(()), max_data.reshape(()))
    sw = _range_scale(min_weight.reshape(()), max_weight.reshape(()))
    out_scale = sd * sw
    if not no_bias and bias is not None:
        bq = jnp.round(bias * out_scale).astype(jnp.int32)
        acc = acc + bq.reshape((1, -1, 1, 1))
    out_max = _INT8_MAX / out_scale
    return acc, (-out_max).reshape((1,)), out_max.reshape((1,))


@register("_contrib_quantized_pooling", num_outputs=3,
          aliases=("quantized_pooling",))
def quantized_pooling(data, min_data, max_data, kernel=(), pool_type="max",
                      global_pool=False, stride=(), pad=(),
                      pooling_convention="valid", count_include_pad=True,
                      layout=None):
    """int8 pooling (reference: quantized_pooling.cc) — pooling runs in
    the quantized domain and the input range passes through unchanged
    (pooling is range-preserving: max picks an existing int8 value; avg
    stays within [min, max]). Closes the r2 gap where a quantized CNN
    fell back to dequantize->fp32->requantize around every pool."""
    import jax.numpy as jnp

    from .nn import pooling

    if pool_type == "max":
        out = pooling(data, kernel=kernel, pool_type="max",
                      global_pool=global_pool, stride=stride, pad=pad,
                      pooling_convention=pooling_convention)
    elif pool_type == "avg":
        # accumulate in float32 (exact for int8 sums), round back to the
        # quantized grid — the reference's integer-average behavior
        acc = pooling(data.astype(jnp.float32), kernel=kernel,
                      pool_type="avg", global_pool=global_pool,
                      stride=stride, pad=pad,
                      pooling_convention=pooling_convention,
                      count_include_pad=count_include_pad)
        out = jnp.clip(jnp.round(acc), -127, 127).astype(data.dtype)
    else:
        from ..base import MXNetError

        raise MXNetError("quantized_pooling: pool_type=%r not supported"
                         % pool_type)
    return out, min_data.reshape((1,)), max_data.reshape((1,))


@register("_contrib_quantized_act", num_outputs=3,
          aliases=("quantized_act", "_contrib_quantized_activation"))
def quantized_act(data, min_data, max_data, act_type="relu"):
    """int8 activation (reference: quantized_activation.cc — relu only).
    relu clamps int8 values at 0, which the existing symmetric scale
    represents exactly, so the thresholds pass through unchanged (the
    reference keeps them and marks FNeedRequantize=false)."""
    import jax.numpy as jnp

    if act_type != "relu":
        from ..base import MXNetError

        raise MXNetError("_contrib_quantized_act only supports "
                         "act_type=relu (reference parity)")
    out = jnp.maximum(data, jnp.int8(0)).astype(data.dtype)
    return out, min_data.reshape((1,)), max_data.reshape((1,))


@register("_contrib_quantized_flatten", num_outputs=3,
          aliases=("quantized_flatten",))
def quantized_flatten(data, min_data, max_data):
    """int8 flatten (reference: quantized_flatten-inl.h — identity values,
    thresholds pass through; only the shape collapses to (batch, -1))."""
    out = data.reshape((data.shape[0], -1))
    return out, min_data.reshape((1,)), max_data.reshape((1,))


@register("_contrib_quantized_concat", num_outputs=3,
          aliases=("quantized_concat",))
def quantized_concat(*args, num_args=None, dim=1):
    """int8 concat (reference: quantized_concat.cc) — inputs are n data
    arrays followed by (min_i, max_i) pairs; every input is rescaled to
    the widest [min, max] range, concatenated, and that range is the
    output's. Input order mirrors the reference FListInputNames
    (arg0..argN-1, arg0_min, arg0_max, arg1_min, ...)."""
    import jax.numpy as jnp

    n = int(num_args) if num_args is not None else len(args) // 3
    datas = args[:n]
    mins = [args[n + 2 * i].reshape(()) for i in range(n)]
    maxs = [args[n + 2 * i + 1].reshape(()) for i in range(n)]
    # widest symmetric range wins (reference: "rescaled by using largest
    # [min, max] pairs")
    out_min = jnp.minimum(jnp.stack(mins).min(), 0.0)
    out_max = jnp.stack(maxs).max()
    out_scale = _range_scale(out_min, out_max)
    parts = []
    for d, mn, mx in zip(datas, mins, maxs):
        s = _range_scale(mn, mx)
        # value = q / s; requantized q' = round(value * out_scale)
        q = jnp.round(d.astype(jnp.float32) * (out_scale / s))
        parts.append(jnp.clip(q, -127, 127).astype(jnp.int8))
    out = jnp.concatenate(parts, axis=int(dim))
    return out, out_min.reshape((1,)), out_max.reshape((1,))
